"""MaintenanceManager: scored background-op scheduling.

Capability parity with the reference (ref:
src/yb/tablet/maintenance_manager.h:154 MaintenanceOp with UpdateStats/
Prepare/Perform; maintenance_manager.cc FindBestOp): every candidate op
reports (ram_anchored, logs_retained_bytes, perf_improvement) and the
scheduler picks, in priority order,
  1. under memory pressure - the op anchoring the most RAM,
  2. with WAL replay debt above log_target_replay_size - the op
     releasing the most log bytes,
  3. otherwise - the op with the highest perf_improvement.

Built-in per-tablet ops (generated dynamically from the live peer list,
like the memory arbiter, rather than registered/unregistered on tablet
open/close): FlushOp (memstore -> SST, releases RAM and WAL),
LogGCOp (drops fully-flushed WAL segments; the only automatic WAL GC
trigger in the server), CompactOp (kicks the compaction picker for
tablets that went idle mid-backlog), and RecoverOp — the capped-
exponential-backoff retry that un-parks tablets in FAILED state after a
background storage error (ref DBImpl::Resume driven by
ErrorHandler::RecoverFromBGError). External subsystems can register
custom MaintenanceOps through register_op() — the TabletServer registers
PrewarmKernelsOp (startup kernel compile) and ScrubTabletsOp (at-rest
integrity scrub + cross-replica digest exchange) this way.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.backoff import RetrySchedule
from yugabyte_tpu.utils.mem_tracker import root_tracker
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("maintenance_manager_polling_interval_s", 0.25,
                  "how often the maintenance scheduler scores ops "
                  "(ref maintenance_manager_polling_interval_ms)")
flags.define_flag("log_target_replay_size_mb", 64,
                  "closed-WAL bytes per tablet above which log-releasing "
                  "ops take priority (ref log_target_replay_size_mb)")
flags.define_flag("background_error_retry_initial_s", 0.5,
                  "first-retry delay for a tablet parked by a background "
                  "storage error; doubles per failure")
flags.define_flag("background_error_retry_max_s", 30.0,
                  "cap on the background-error retry delay")
flags.define_flag("compaction_prewarm_kernels", 0,
                  "compile the common compaction-kernel shape buckets at "
                  "tserver startup (one-shot maintenance op) so first "
                  "compactions load cached executables instead of paying "
                  "the full XLA compile; enable on real accelerators")


class MaintenanceOpStats:
    """One op's current utility (ref maintenance_manager.h:62)."""

    __slots__ = ("runnable", "ram_anchored", "logs_retained_bytes",
                 "perf_improvement")

    def __init__(self):
        self.runnable = False
        self.ram_anchored = 0
        self.logs_retained_bytes = 0
        self.perf_improvement = 0.0


class MaintenanceOp:
    """Base class for registered ops (ref maintenance_manager.h:154)."""

    def __init__(self, name: str):
        self.name = name

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        raise NotImplementedError

    def perform(self) -> None:
        raise NotImplementedError


class _FlushOp(MaintenanceOp):
    def __init__(self, peer, flush_releasable: int):
        super().__init__(f"flush:{peer.tablet_id}")
        self._peer = peer
        self._flush_releasable = flush_releasable

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        ram = self._peer.tablet.memstore_bytes()
        stats.runnable = ram > 0
        stats.ram_anchored = ram
        # only the bytes a flush can ACTUALLY release: the raft lagging-
        # peer watermark and CDC retention still pin the WAL after a
        # flush, so scoring all closed segments would flush near-empty
        # memstores forever while freeing nothing (snapshotted once per
        # poll round by _candidate_ops — one WAL scan serves both ops)
        stats.logs_retained_bytes = self._flush_releasable

    def perform(self) -> None:
        self._peer.flush_and_gc_wal()


class _LogGCOp(MaintenanceOp):
    def __init__(self, peer, freeable: int):
        super().__init__(f"log_gc:{peer.tablet_id}")
        self._peer = peer
        self._freeable = freeable

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        stats.runnable = self._freeable > 0
        stats.logs_retained_bytes = self._freeable

    def perform(self) -> None:
        self._peer.gc_wal()


class _CompactOp(MaintenanceOp):
    def __init__(self, peer):
        super().__init__(f"compact:{peer.tablet_id}")
        self._peer = peer

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        # L0 backlog beyond the picker's merge width = perf debt: reads
        # touch every overlapping run (ref: read amplification scoring)
        t = self._peer.tablet
        trigger = flags.get_flag("universal_compaction_min_merge_width")
        backlog = 0
        for db in (t.regular_db, t.intents_db):
            backlog = max(backlog, db.n_live_files - trigger)
        stats.runnable = backlog > 0
        stats.perf_improvement = float(backlog)

    def perform(self) -> None:
        t = self._peer.tablet
        for db in (t.regular_db, t.intents_db):
            db.maybe_schedule_compaction()


class PrewarmKernelsOp(MaintenanceOp):
    """One-shot startup compile of the common compaction-kernel shape
    buckets (ops/run_merge.prewarm_buckets): with the shape-bucket lattice
    + the persistent compilation cache, every bucket a tablet's lifetime
    of compactions needs is a one-time cost — paid HERE, before traffic,
    instead of stalling the first real compaction of each shape for the
    full XLA compile (107s measured on the tunnel TPU). Each bucket's
    warm covers the whole chained-compaction surface: both is_major merge
    variants, the device-resident restage/survivor-scan/span-gather
    programs (the L0->L1->L2 write-through path), and on TPU the pallas
    tournament kernel.

    Scored just below recovery (warm kernels beat compaction debt: every
    queued compaction stalls on a cold bucket) and unrunnable after the
    first successful run. Gated by the compaction_prewarm_kernels flag
    (default off — the CPU fallback's compiles are cheap enough to not
    spend test/startup time on)."""

    PREWARM_SCORE = 1e8

    def __init__(self, shapes=None, enabled_fn=None, mesh=None):
        super().__init__("prewarm_kernels")
        self._shapes = shapes
        self._mesh = mesh
        self._enabled_fn = enabled_fn or (
            lambda: bool(flags.get_flag("compaction_prewarm_kernels")))
        self.done = False

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        stats.runnable = not self.done and self._enabled_fn()
        stats.perf_improvement = self.PREWARM_SCORE

    def perform(self) -> None:
        from yugabyte_tpu.ops import block_codec, point_read, run_merge, scan
        from yugabyte_tpu.storage import offload_policy
        from yugabyte_tpu.storage.bucket_health import health_board
        from yugabyte_tpu.utils.metrics import publish_compile_surface
        board = health_board()
        shapes = list(self._shapes if self._shapes is not None
                      else run_merge._PREWARM_SHAPES)
        # AOT priority from the health board: the highest-traffic COLD
        # buckets (jobs the policy routed native while unamortized)
        # compile first, so the order traffic arrives in is the order
        # the compile budget is spent in
        prio = {key[1]: i for i, key in enumerate(
            k for k in board.prewarm_priorities()
            if k[0] == "run_merge_fused")}
        shapes.sort(key=lambda s: prio.get((s[0], s[1]), len(prio)))
        n = run_merge.prewarm_buckets(shapes)
        for s in shapes:
            # the compile cost is paid: COLD -> WARMING, so the policy
            # gate stops routing these buckets native
            board.record_prewarmed("run_merge_fused", (s[0], s[1]))
        # the batched point-read families (serve-path kernels) warm in
        # the same pass — their first real multi_get batch must load a
        # cached executable, not stall a read on an XLA compile
        n += point_read.prewarm_point_read()
        # query-pushdown families (fused filtered/aggregating scans):
        # the first SELECT count(*) ... WHERE must not pay the compile.
        # Only in FULL prewarm mode (shapes=None): a bounded-shapes op —
        # the unit-test lifecycle mode — must not spend ~10s/executable
        # on the 40-program pushdown lattice.
        if self._shapes is None:
            n += scan.prewarm_scan_pushdown()
            # device block codec (stage A decode / stage C encode): the
            # first cold compaction chain must not stall on its compile
            n += block_codec.prewarm_block_codec()
            if self._mesh is not None \
                    and getattr(self._mesh, "devices", None) is not None \
                    and self._mesh.devices.size > 1:
                # mesh families: the key-range-sharded dist step and the
                # multi-tablet pool wave program — a pooled tablet's
                # first wave must load a cached executable too
                from yugabyte_tpu.parallel.dist_compact import (
                    prewarm_dist_compact)
                n += prewarm_dist_compact(self._mesh)
        # expose the declared compile surface (committed kernel
        # manifest) next to the bucket hit/miss counters: the warm cache
        # must cover exactly this many executables
        publish_compile_surface(offload_policy.declared_surface_counts())
        self.done = True
        TRACE("maintenance: prewarmed %d compaction kernel executables",
              n)


class ScrubTabletsOp(MaintenanceOp):
    """Background at-rest integrity scrubber: deep-verifies each RUNNING
    tablet's SSTs (block CRCs + footer + index/bloom consistency) on a
    ``--scrub_interval_s`` cadence, reads throttled through the
    process-wide ``--scrub_bytes_per_sec`` token bucket, one tablet per
    perform() so the scheduler stays responsive. When the scrubbed
    tablet is a Raft leader, a cross-replica digest exchange (the
    ``checksum_tablet`` RPC, via the server-provided ``digest_check``
    hook) follows the local scrub — the detector for divergence that
    byte-level CRCs cannot see.

    Scored just above zero: scrubbing is strictly idle-time work — any
    flush/compaction/recovery debt outranks it (the reference's
    VerifyChecksum sweeps are likewise background-priority)."""

    SCRUB_SCORE = 0.05

    def __init__(self, peers_fn: Callable[[], List],
                 digest_check: Optional[Callable[[object], int]] = None):
        super().__init__("scrub_tablets")
        self._peers_fn = peers_fn
        self._digest_check = digest_check
        # tablet_id -> monotonic ts of its last scrub; tablets never
        # scrubbed age from op construction (a fresh server's files were
        # just written/bootstrapped — scrubbing them immediately would
        # burn startup I/O for nothing)
        self._last: Dict[str, float] = {}
        self._t0 = time.monotonic()

    def _due_peer(self):
        """Most-overdue RUNNING tablet at or past the interval, else
        None."""
        from yugabyte_tpu.tablet.tablet_peer import STATE_RUNNING
        from yugabyte_tpu.storage import integrity  # noqa: F401 (flags)
        interval = float(flags.get_flag("scrub_interval_s"))
        if interval <= 0:
            return None
        now = time.monotonic()
        best, best_age = None, interval
        live = set()
        for peer in self._peers_fn():
            live.add(peer.tablet_id)
            if peer.state != STATE_RUNNING:
                continue
            age = now - self._last.get(peer.tablet_id, self._t0)
            if age >= best_age:
                best, best_age = peer, age
        for tid in [t for t in self._last if t not in live]:
            del self._last[tid]  # deleted/moved tablets drop tracking
        return best

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        stats.runnable = self._due_peer() is not None
        stats.perf_improvement = self.SCRUB_SCORE

    def perform(self) -> None:
        from yugabyte_tpu.storage import integrity
        peer = self._due_peer()
        if peer is None:
            return
        self._last[peer.tablet_id] = time.monotonic()
        report = peer.tablet.scrub(limiter=integrity.scrub_rate_limiter())
        mismatches = 0
        if self._digest_check is not None and not report["corrupt"] \
                and peer.raft.is_leader():
            mismatches = self._digest_check(peer)
        prev = peer.scrub_state or {}
        peer.scrub_state = {
            "last_scrub_ts": time.time(),
            "files": report["files"], "bytes": report["bytes"],
            "corrupt": prev.get("corrupt", 0) + len(report["corrupt"]),
            "replica_mismatches": prev.get("replica_mismatches", 0)
            + mismatches,
        }
        if report["corrupt"]:
            TRACE("scrub: tablet %s has %d corrupt SST(s) — quarantined "
                  "and parked for rebuild: %s", peer.tablet_id,
                  len(report["corrupt"]), report["corrupt"])
        else:
            TRACE("scrub: tablet %s clean (%d files, %d bytes, %d "
                  "replica digest mismatches)", peer.tablet_id,
                  report["files"], report["bytes"], mismatches)


class _RecoverOp(MaintenanceOp):
    """Un-park a FAILED tablet (ref ErrorHandler::RecoverFromBGError):
    in-place retry of the parked flush/compaction via the tablet
    manager's recover hook, paced by a per-tablet capped exponential
    backoff so a persistently broken disk is not hammered every poll."""

    # outranks every compaction-debt score: a FAILED tablet rejects writes
    RECOVERY_SCORE = 1e9

    def __init__(self, peer, schedule: RetrySchedule, recover_fn):
        super().__init__(f"recover:{peer.tablet_id}")
        self._peer = peer
        self._schedule = schedule
        self._recover_fn = recover_fn

    def update_stats(self, stats: MaintenanceOpStats) -> None:
        stats.runnable = self._schedule.ready()
        stats.perf_improvement = self.RECOVERY_SCORE

    def perform(self) -> None:
        if self._recover_fn(self._peer):
            self._schedule.reset()
        else:
            delay = self._schedule.record_failure()
            TRACE("maintenance: recovery of %s failed; next attempt in "
                  "%.2fs", self._peer.tablet_id, delay)


class MaintenanceManager:
    """One per TabletServer (ref maintenance_manager.cc)."""

    def __init__(self, peers_fn: Callable[[], List], metric_entity=None,
                 memory_pressure_fn: Optional[Callable[[], bool]] = None,
                 recover_fn: Optional[Callable[[object], bool]] = None):
        self._peers_fn = peers_fn
        # recover_fn(peer) -> bool; default = the peer's in-place recovery
        # (clears DB background errors). The tablet server passes the
        # manager's recover_failed_tablet for full re-bootstrap coverage.
        self._recover_fn = recover_fn or (lambda peer: peer.try_recover())
        # _recover_backoff is scheduler-thread-only state (the loop and
        # test-driven run_once are never concurrent by contract)
        self._recover_backoff: Dict[str, RetrySchedule] = {}
        from yugabyte_tpu.utils import lock_rank
        self._registered: List[MaintenanceOp] = []  # guarded-by: _reg_lock
        self._reg_lock = lock_rank.tracked(threading.Lock(),
                                           "maintenance._reg_lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._memory_pressure = (memory_pressure_fn or
                                 (lambda: root_tracker()
                                  .soft_limit_exceeded().exceeded))
        self._c_ops = self._h_dur = None
        if metric_entity is not None:
            self._c_ops = metric_entity.counter(
                "maintenance_ops_performed_total",
                "background maintenance ops run")
            self._h_dur = metric_entity.histogram(
                "maintenance_op_duration_ms", "maintenance op wall time")
        self.last_op_name: Optional[str] = None   # observability/tests

    # ------------------------------------------------------------ lifecycle
    def init(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="maintenance-mgr")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def register_op(self, op: MaintenanceOp) -> None:
        with self._reg_lock:
            self._registered.append(op)

    def unregister_op(self, op: MaintenanceOp) -> None:
        with self._reg_lock:
            if op in self._registered:
                self._registered.remove(op)

    # ------------------------------------------------------------ scheduling
    def _retry_schedule(self, tablet_id: str) -> RetrySchedule:
        sched = self._recover_backoff.get(tablet_id)
        if sched is None:
            sched = self._recover_backoff[tablet_id] = RetrySchedule(
                initial_s=flags.get_flag("background_error_retry_initial_s"),
                max_s=flags.get_flag("background_error_retry_max_s"))
        return sched

    def _candidate_ops(self) -> List[MaintenanceOp]:
        from yugabyte_tpu.tablet.tablet_peer import STATE_FAILED
        ops: List[MaintenanceOp] = []
        live_ids = set()
        for peer in self._peers_fn():
            live_ids.add(peer.tablet_id)
            if peer.state == STATE_FAILED:
                # a parked tablet has nothing to flush/GC/compact — its
                # only maintenance is the backoff-paced recovery retry
                ops.append(_RecoverOp(peer,
                                      self._retry_schedule(peer.tablet_id),
                                      self._recover_fn))
                continue
            # one WAL-directory scan per peer per round, shared by both
            # log-scoring ops (listdir+stat per op per poll would hammer
            # the Log lock on servers with many idle tablets)
            try:
                freeable = peer.log.gc_candidate_bytes(peer.wal_anchor())
                flush_releasable = peer.log.gc_candidate_bytes(
                    peer.wal_anchor(assume_flushed=True))
            except Exception as e:
                TRACE("maintenance: WAL scoring for %s failed: %s",
                      getattr(peer, "tablet_id", "?"), e)
                freeable = flush_releasable = 0
            ops.append(_FlushOp(peer, flush_releasable))
            ops.append(_LogGCOp(peer, freeable))
            ops.append(_CompactOp(peer))
        # drop backoff state for tablets that went away (deleted / moved)
        for tid in list(self._recover_backoff):
            if tid not in live_ids:
                del self._recover_backoff[tid]
        with self._reg_lock:
            ops.extend(self._registered)
        return ops

    def find_best_op(self) -> Optional[MaintenanceOp]:
        """The reference's FindBestOp policy (maintenance_manager.cc):
        memory pressure -> max ram_anchored; log debt above target ->
        max logs_retained; else max perf_improvement."""
        scored = []
        for op in self._candidate_ops():
            stats = MaintenanceOpStats()
            try:
                op.update_stats(stats)
            except Exception as e:
                # never silently disable a broken op: a tablet whose flush
                # scoring always throws would pile up debt with no signal
                TRACE("maintenance op %s update_stats failed: %s",
                      op.name, e)
                continue
            if stats.runnable:
                scored.append((op, stats))
        if not scored:
            return None
        if self._memory_pressure():
            best = max(scored, key=lambda s: s[1].ram_anchored)
            if best[1].ram_anchored > 0:
                return best[0]
        log_target = flags.get_flag("log_target_replay_size_mb") << 20
        loggy = max(scored, key=lambda s: s[1].logs_retained_bytes)
        if loggy[1].logs_retained_bytes > log_target:
            return loggy[0]
        perf = max(scored, key=lambda s: s[1].perf_improvement)
        if perf[1].perf_improvement > 0:
            return perf[0]
        # fall back to any freeable log bytes (cheap housekeeping)
        if loggy[1].logs_retained_bytes > 0:
            return loggy[0]
        return None

    def run_once(self) -> Optional[str]:
        """Score + perform at most one op; returns its name (tests drive
        this synchronously; the background loop calls it repeatedly)."""
        op = self.find_best_op()
        if op is None:
            return None
        t0 = time.monotonic()
        try:
            op.perform()
        except Exception as e:
            TRACE("maintenance op %s failed: %s", op.name, e)
            return None
        self.last_op_name = op.name
        if self._c_ops is not None:
            self._c_ops.increment()
            self._h_dur.increment((time.monotonic() - t0) * 1e3)
        return op.name

    def _loop(self) -> None:
        # interval re-read each round: the flag is runtime-tunable
        while not self._stop.wait(
                flags.get_flag("maintenance_manager_polling_interval_s")):
            try:
                self.run_once()
            except Exception as e:
                TRACE("maintenance loop error: %s", e)
