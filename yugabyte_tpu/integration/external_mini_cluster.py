"""ExternalMiniCluster: real master/tserver PROCESSES for crash testing.

Capability parity with the reference's harness (ref:
src/yb/integration-tests/external_mini_cluster.h — spawns real
yb-master/yb-tserver binaries, kills them with SIGKILL, restarts them on
the same data dirs; cluster_verifier.h — cross-replica checksum
verification). The in-process MiniCluster cannot test crashes — a Python
thread cannot be kill -9'd; these nodes can.

Crash points inside a node are armed via env (utils/sync_point.py):
    cluster.restart_tserver(0, crash_point="db.flush:before_manifest")
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from yugabyte_tpu.client.client import YBClient


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Node:
    def __init__(self, role: str, server_id: str, fs_root: str, port: int,
                 master_addrs: str, rf: int):
        self.role = role
        self.server_id = server_id
        self.fs_root = fs_root
        self.port = port
        self.master_addrs = master_addrs
        self.rf = rf
        self.proc: Optional[subprocess.Popen] = None

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self, crash_point: Optional[str] = None,
              wait_ready: bool = True,
              extra_flags: Optional[Dict[str, object]] = None) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("YBTPU_CRASH_POINT", None)
        cmd = [sys.executable, "-m",
               "yugabyte_tpu.integration.node_runner", self.role,
               "--fs-root", self.fs_root, "--port", str(self.port),
               "--server-id", self.server_id, "--rf", str(self.rf)]
        if crash_point:
            # armed post-startup so bootstrap-time hits don't kill the
            # node before READY
            cmd += ["--crash-point", crash_point]
        for k, v in (extra_flags or {}).items():
            cmd += ["--flag", f"{k}={v}"]
        if self.master_addrs:
            cmd += ["--master-addrs", self.master_addrs]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)
        if wait_ready:
            line = self.proc.stdout.readline()
            if not line.startswith("READY"):
                raise RuntimeError(
                    f"{self.role} {self.server_id} failed to start: {line!r}")

    def kill9(self) -> None:
        """SIGKILL — no shutdown hooks, no flushes (the crash under test)."""
        if self.proc is not None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass
            self.proc.wait()
            self.proc = None

    def wait_exit(self, timeout_s: float = 30.0) -> int:
        assert self.proc is not None
        rc = self.proc.wait(timeout=timeout_s)
        self.proc = None
        return rc

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ExternalMiniCluster:
    def __init__(self, fs_root: str, num_tservers: int = 3, rf: int = 3,
                 default_flags: Optional[Dict[str, object]] = None):
        """default_flags: flag overrides applied to EVERY node at start
        and restart (e.g. relaxed raft election timing for a soak on an
        oversubscribed CI core)."""
        self.fs_root = fs_root
        self.rf = rf
        self.default_flags = dict(default_flags or {})
        os.makedirs(fs_root, exist_ok=True)
        mport = _free_port()
        self.master = _Node("master", "m0",
                            os.path.join(fs_root, "master"), mport, "", rf)
        self.tservers: List[_Node] = [
            _Node("tserver", f"ets{i}", os.path.join(fs_root, f"ts{i}"),
                  _free_port(), f"127.0.0.1:{mport}", rf)
            for i in range(num_tservers)]

    def start(self) -> "ExternalMiniCluster":
        self.master.start(extra_flags=self.default_flags or None)
        for ts in self.tservers:
            ts.start(extra_flags=self.default_flags or None)
        return self

    def new_client(self) -> YBClient:
        return YBClient([self.master.address])

    def wait_tservers_alive(self, n: int, timeout_s: float = 60.0) -> None:
        """Block until the master reports >= n live tservers (fresh starts
        and post-kill restarts race heartbeat registration)."""
        client = self.new_client()
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    live = [t for t in client.list_tservers()
                            if t.get("alive")]
                    if len(live) >= n:
                        return
                except Exception:  # noqa: BLE001 — master still starting
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{n} live tservers not reached in {timeout_s}s")
                time.sleep(0.3)
        finally:
            client.close()

    def wait_table_leaders(self, client: YBClient, table_id: str,
                           timeout_s: float = 60.0) -> None:
        """Deadline-poll the master's location map until EVERY tablet of
        the table reports a leader (the external-cluster twin of
        MiniCluster.wait_for_table_leaders — the deflake primitive for
        create-then-write: a fresh tablet's first election can outlast a
        writer's retry budget)."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                locs = client._master_call("get_table_locations",
                                           table_id=table_id)
                if locs and all(loc.get("leader") for loc in locs):
                    return
            except Exception:  # noqa: BLE001 — tablets still registering
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"leaders of table {table_id} not elected in "
                    f"{timeout_s}s")
            time.sleep(0.3)

    def restart_tserver(self, i: int, crash_point: Optional[str] = None,
                        extra_flags: Optional[Dict[str, object]] = None
                        ) -> None:
        self.tservers[i].kill9()
        merged = dict(self.default_flags)
        merged.update(extra_flags or {})
        self.tservers[i].start(crash_point=crash_point,
                               extra_flags=merged or None)

    def shutdown(self) -> None:
        for ts in self.tservers:
            ts.kill9()
        self.master.kill9()

    # ------------------------------------------------------------ verifier
    def verify_replica_checksums(self, client: YBClient, table,
                                 timeout_s: float = 60.0) -> Dict[str, int]:
        """Every replica of every tablet must hold an identical committed
        state at one read time (ref cluster_verifier.h). Returns
        tablet_id -> checksum."""
        locs = client._master_call("get_table_locations",
                                   table_id=table.table_id)
        out: Dict[str, int] = {}
        deadline = time.monotonic() + timeout_s

        def _until(fn):
            while True:
                try:
                    return fn()
                except Exception:  # noqa: BLE001 — converging/failing over
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.3)

        # one read time per tablet: pinned by a leader scan (tried through
        # the replicas — whichever currently leads answers)
        for loc in locs:
            tablet_id = loc["tablet_id"]
            addrs = [rep["addr"] for rep in loc["replicas"]
                     if rep["addr"] is not None]

            def _pin_read_ht():
                last = None
                for addr in addrs:
                    try:
                        return client._messenger.call(
                            addr, "tserver", "scan", tablet_id=tablet_id,
                            limit=1)["read_ht"]
                    except Exception as e:  # noqa: BLE001 — not the leader
                        last = e
                raise last  # type: ignore[misc]

            read_ht = _until(_pin_read_ht)
            sums = {}
            for rep in loc["replicas"]:
                addr = rep["addr"]
                if addr is None:
                    continue
                resp = _until(lambda a=addr: client._messenger.call(
                    a, "tserver", "checksum_tablet", timeout_s=30.0,
                    tablet_id=tablet_id, read_ht=read_ht))
                sums[rep["server_id"]] = resp["checksum"]
            assert len(set(sums.values())) == 1, (
                f"replica divergence on {tablet_id}: {sums}")
            out[tablet_id] = next(iter(sums.values()))
        return out
