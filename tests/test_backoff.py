"""utils/backoff.py: decorrelated-jitter Backoff + RetrySchedule pacing,
and the swallowed-error lint wired into tier-1."""

import os
import random
import sys
import time

from yugabyte_tpu.utils.backoff import Backoff, RetrySchedule

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBackoff:
    def test_delays_bounded_and_jittered(self):
        b = Backoff(base_s=0.05, cap_s=1.0, rng=random.Random(7))
        delays = [b.next_delay() for _ in range(50)]
        assert all(0.0 <= d <= 1.0 for d in delays)
        assert b.attempts == 50
        # decorrelated jitter: values are not all identical (no lockstep)
        assert len({round(d, 6) for d in delays}) > 10
        # and the early delays trend upward from the base toward the cap
        assert max(delays[:3]) < 1.0 or delays[0] < delays[-1]

    def test_two_backoffs_desynchronize(self):
        a = Backoff(base_s=0.05, cap_s=2.0, rng=random.Random(1))
        b = Backoff(base_s=0.05, cap_s=2.0, rng=random.Random(2))
        assert [a.next_delay() for _ in range(5)] != \
            [b.next_delay() for _ in range(5)]

    def test_deadline_clamps_and_expires(self):
        b = Backoff(base_s=10.0, cap_s=60.0, deadline_s=0.1)
        d = b.next_delay()
        assert d <= 0.1  # clamped to the remaining deadline
        time.sleep(0.12)
        assert b.expired
        assert not b.sleep()  # no sleep once expired

    def test_sleep_returns_true_within_deadline(self):
        b = Backoff(base_s=0.001, cap_s=0.002, deadline_s=5.0)
        assert b.sleep()


class TestRetrySchedule:
    def test_exponential_spacing_capped(self):
        rng = random.Random(3)
        s = RetrySchedule(initial_s=0.1, max_s=1.0, rng=rng)
        assert s.ready()
        delays = [s.record_failure() for _ in range(8)]
        # grows ~2x per failure until the cap (+-25% jitter)
        assert delays[0] <= 0.1 * 1.25
        assert delays[1] <= 0.2 * 1.25
        assert all(d <= 1.0 * 1.25 for d in delays)
        assert delays[-1] >= 1.0 * 0.75  # capped, not unbounded
        assert not s.ready()

    def test_reset_rearms_immediately(self):
        s = RetrySchedule(initial_s=5.0, max_s=30.0)
        s.record_failure()
        assert not s.ready()
        s.reset()
        assert s.ready() and s.failures == 0

    def test_becomes_ready_after_delay(self):
        s = RetrySchedule(initial_s=0.01, max_s=0.02)
        s.record_failure()
        deadline = time.monotonic() + 2.0
        while not s.ready():
            assert time.monotonic() < deadline
            time.sleep(0.005)

    def test_deadline_clamps_and_expires(self):
        """Per-op budget: delays clamp to the remaining budget and the
        schedule stops offering attempts once it is spent."""
        s = RetrySchedule(initial_s=100.0, max_s=200.0, deadline_s=0.05)
        d = s.record_failure()
        assert d <= 0.05, "delay must clamp to the remaining budget"
        assert s.remaining_s() is not None
        time.sleep(0.07)
        assert s.expired
        assert not s.ready(), "expired schedule must not offer attempts"

    def test_unbounded_schedule_never_expires(self):
        s = RetrySchedule(initial_s=0.01)
        assert s.remaining_s() is None
        for _ in range(5):
            s.record_failure()
        assert not s.expired


class TestClientOpDeadline:
    """Satellite: client retries honor an overall per-op deadline and
    surface DeadlineExceeded instead of retrying past it."""

    def test_backoff_remaining_clamps(self):
        b = Backoff(base_s=0.01, cap_s=0.02, deadline_s=0.5)
        r = b.remaining_s()
        assert r is not None and 0 < r <= 0.5
        assert Backoff(base_s=0.01).remaining_s() is None

    def test_master_call_surfaces_deadline_exceeded(self):
        """A client hammering an unreachable master stops at the per-op
        deadline with TIMED_OUT, not after burning all retry rounds."""
        from yugabyte_tpu.client.client import YBClient
        from yugabyte_tpu.utils import flags
        from yugabyte_tpu.utils.status import Code, StatusError
        old = flags.get_flag("client_op_timeout_s")
        flags.set_flag("client_op_timeout_s", 0.3)
        client = YBClient(["127.0.0.1:1"])  # nothing listens there
        try:
            t0 = time.monotonic()
            try:
                client.list_namespaces()
                raise AssertionError("expected a deadline failure")
            except StatusError as e:
                assert e.status.code == Code.TIMED_OUT, e.status
                assert "deadline" in e.status.message
            # far below the 12-round full-backoff walk
            assert time.monotonic() - t0 < 10.0
        finally:
            flags.set_flag("client_op_timeout_s", old)
            client.close()


def test_no_swallowed_errors_in_storage_layers():
    """CI wiring for tools/lint_swallowed_errors.py: storage/, consensus/
    and tablet/ must route every broadly-caught error to the
    background-error slot or TRACE — silent swallowing is how an injected
    disk fault becomes corruption instead of a contained FAILED tablet."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import lint_swallowed_errors as lint
    finally:
        sys.path.pop(0)
    offenses = lint.check_paths(REPO_ROOT)
    assert not offenses, "\n".join(
        f"{p}:{ln}: {msg}" for p, ln, msg in offenses)
