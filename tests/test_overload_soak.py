"""Overload soak (tier-2, slow): graceful degradation end to end.

An RF3 MiniCluster is first measured at a sustainable paced load, then
offered >= 5x that rate with every shedding layer live (bounded RPC
queue, write-pressure admission, client retry budgets). The soak
asserts the overload-protection contract:

  - ZERO acked-write loss: every op whose session flush acked reads
    back after the storm (per-op demux decides ackedness);
  - memstore bytes never exceed the server memstore tracker limit
    (sampled continuously through the storm);
  - every rejection the clients see is TYPED retryable (overloaded
    extras / retryable codes) — nothing surfaces as an opaque failure —
    and the servers COUNTED their shedding (queue overflow + write
    throttle totals);
  - goodput under shedding stays >= 70% of the pre-overload rate
    (shedding degrades gracefully instead of collapsing);
  - the cluster returns to healthy — no hard/soft pressure signals, no
    FAILED tablets, empty RPC queues — within 30s of load removal;
  - a chaos cycle (PR-6 nemesis leader partition) under renewed
    overload still loses nothing acked and converges healthy.

Run with: pytest tests/test_overload_soak.py -m slow
YBTPU_SOAK_SECONDS scales the load windows (default 8s).
"""

import os
import threading
import time

import pytest

import yugabyte_tpu.storage.db  # noqa: F401 — registers flags
import yugabyte_tpu.storage.offload_policy  # noqa: F401 — registers flags
import yugabyte_tpu.tserver.tablet_memory_manager  # noqa: F401 — flags
from yugabyte_tpu.client.session import SessionFlushError, YBSession
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.chaos import NemesisController
from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                   MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.metrics import serve_path_metrics
from yugabyte_tpu.utils.status import Code, StatusError

SCHEMA = Schema(columns=[ColumnSchema("k", DataType.STRING),
                         ColumnSchema("v", DataType.STRING)],
                num_hash_key_columns=1)

_RETRYABLE_CODES = {Code.SERVICE_UNAVAILABLE, Code.TIMED_OUT,
                    Code.TRY_AGAIN, Code.BUSY, Code.NOT_FOUND}


def _classify(err, overloaded_seen, bad):
    """Every error a client surfaces under overload must be typed
    retryable; anything else is a contract violation collected in
    `bad`. Returns nothing — mutates the two accumulators."""
    if isinstance(err, SessionFlushError):
        for _t, _op, sub in err.per_op:
            _classify(sub, overloaded_seen, bad)
        return
    extra = getattr(err, "extra", {}) or {}
    if extra.get("overloaded"):
        overloaded_seen.append(err)
        return
    if isinstance(err, StatusError) and err.status.code in _RETRYABLE_CODES:
        return
    if extra.get("not_leader") or extra.get("replication_aborted") \
            or extra.get("tablet_failed"):
        return
    bad.append(err)


class _PacedWriter(threading.Thread):
    """Paced batched writer: attempts `rate` ops/s in `batch` -op session
    flushes; keys are globally unique so ackedness is exact. A batch's
    acked set = batch minus the per-op demux failures."""

    def __init__(self, client, table, wid, rate, batch=50,
                 value_bytes=512):
        super().__init__(daemon=True, name=f"ovl-writer-{wid}")
        self.client = client
        self.table = table
        self.wid = wid
        self.rate = rate
        self.batch = batch
        self.value = "v" * value_bytes
        self.stop_ev = threading.Event()
        self.acked = set()
        self.offered = 0
        self.overloaded_seen = []
        self.bad = []
        self.errors = 0
        self._seq = 0

    def _key(self, seq):
        return f"w{self.wid}-{seq:08d}"

    def run(self):
        session = YBSession(self.client)
        period = self.batch / self.rate
        while not self.stop_ev.is_set():
            t0 = time.monotonic()
            keys = []
            for _ in range(self.batch):
                k = self._key(self._seq)
                self._seq += 1
                keys.append(k)
                session.apply(self.table, QLWriteOp(
                    WriteOpKind.INSERT, DocKey(hash_components=(k,)),
                    {"v": self.value}))
            self.offered += len(keys)
            try:
                session.flush()
                self.acked.update(keys)
            except Exception as e:  # noqa: BLE001 — classified below
                self.errors += 1
                _classify(e, self.overloaded_seen, self.bad)
                if isinstance(e, SessionFlushError):
                    failed = {op.doc_key.hash_components[0]
                              for _t, op, _e in e.per_op}
                    self.acked.update(k for k in keys if k not in failed)
            elapsed = time.monotonic() - t0
            if elapsed < period:
                self.stop_ev.wait(period - elapsed)


class _Sampler(threading.Thread):
    """Continuously samples every tserver's memstore tracker and the
    admission signals; records the worst ratios observed."""

    def __init__(self, cluster):
        super().__init__(daemon=True, name="ovl-sampler")
        self.cluster = cluster
        self.stop_ev = threading.Event()
        self.max_memstore_ratio = 0.0
        self.max_signal_score = 0.0
        self.samples = 0

    def run(self):
        while not self.stop_ev.is_set():
            for ts in self.cluster.tservers:
                try:
                    tracker = ts.memory_manager.memstore_tracker
                    if tracker.limit > 0:
                        ratio = tracker.consumption() / tracker.limit
                        self.max_memstore_ratio = max(
                            self.max_memstore_ratio, ratio)
                    for tid in ts.tablet_manager.tablet_ids():
                        peer = ts.tablet_manager.get_tablet(tid)
                        for s in peer.tablet.admission.signals():
                            self.max_signal_score = max(
                                self.max_signal_score, s.score)
                except Exception:  # noqa: BLE001 — server mid-churn
                    continue
            self.samples += 1
            self.stop_ev.wait(0.1)


def _run_writers(client, table, n, total_rate, seconds, wid_base=0):
    writers = [_PacedWriter(client, table, wid_base + i,
                            rate=total_rate / n)
               for i in range(n)]
    for w in writers:
        w.start()
    time.sleep(seconds)
    for w in writers:
        w.stop_ev.set()
    for w in writers:
        w.join(timeout=120)
    return writers


def _shed_totals(cluster):
    m = serve_path_metrics()
    queue_overflow = sum(
        ts.messenger._c_queue_overflow.value()
        for ts in cluster.tservers)
    expired = sum(ts.messenger._c_expired_in_queue.value()
                  for ts in cluster.tservers)
    return {
        "write_throttle_rejections_total": m.counter(
            "write_throttle_rejections_total",
            "writes rejected retryably by the write-pressure "
            "state machine").value(),
        "rpc_queue_overflow_total": queue_overflow,
        "rpc_calls_expired_in_queue_total": expired,
    }


def _overflow_burst(cluster, client, table, keys, n_threads=48):
    """Deterministically exercise the bounded-queue shed path: shrink
    the (runtime-mutable) queue depth, fire a thicket of concurrent
    batched reads, restore. Every error must be typed retryable; the
    client retry loops (hint-floored backoff + budget) are what make
    the burst converge."""
    overloaded_seen, bad = [], []
    old_depth = flags.get_flag("rpc_service_queue_depth")
    flags.set_flag("rpc_service_queue_depth", 2)
    try:
        def rd():
            try:
                client.multi_read(table, [DocKey(hash_components=(k,))
                                          for k in keys])
            except Exception as e:  # noqa: BLE001 — classified below
                _classify(e, overloaded_seen, bad)

        threads = [threading.Thread(target=rd, daemon=True)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        flags.set_flag("rpc_service_queue_depth", old_depth)
    return overloaded_seen, bad


def _wait_recovered(cluster, timeout_s=30.0):
    """The recovery bar: within timeout_s of load removal every tablet
    must read healthy — no hard/soft pressure signal, RUNNING state,
    empty service queues."""
    from yugabyte_tpu.tablet.tablet_peer import STATE_FAILED
    deadline = time.monotonic() + timeout_s
    last = ""
    while time.monotonic() < deadline:
        problems = []
        for ts in cluster.tservers:
            if ts.messenger._service_pool.queue_len():
                problems.append(f"{ts.server_id}: rpc queue nonempty")
            for tid in ts.tablet_manager.tablet_ids():
                peer = ts.tablet_manager.get_tablet(tid)
                if peer.state == STATE_FAILED:
                    problems.append(f"{ts.server_id}/{tid}: FAILED "
                                    f"({peer.failed_status})")
                    continue
                for s in peer.tablet.admission.signals():
                    if s.hard or s.score > 0:
                        problems.append(
                            f"{ts.server_id}/{tid}: {s.name} pressure "
                            f"({s.detail})")
        if not problems:
            return
        last = "; ".join(problems[:6])
        time.sleep(0.25)
    raise AssertionError(
        f"cluster not healthy within {timeout_s}s of load removal: "
        f"{last}")


def _verify_acked_present(client, table, acked):
    present = set()
    for row in client.scan(table, page_size=4096):
        present.add(row.to_dict(SCHEMA)["k"])
    missing = sorted(acked - present)
    assert not missing, (
        f"ACKED writes lost under overload: {missing[:10]} "
        f"(+{len(missing) - 10 if len(missing) > 10 else 0} more; "
        f"{len(acked)} acked, {len(present)} present)")
    return present


@pytest.mark.slow
def test_overload_soak(tmp_path):
    hold = float(os.environ.get("YBTPU_SOAK_SECONDS", 8))
    knobs = {
        # serve-path config for an oversubscribed CI core (PR-11 notes):
        # native offload + relaxed election timing
        "device_offload_mode": "native",
        "point_read_batched": False,
        "raft_heartbeat_interval_ms": 100,
        "leader_failure_max_missed_heartbeat_periods": 20,
        # overload shape: small per-DB memstores (frequent self-flush),
        # a 4 MiB per-server memstore budget with a fast arbiter, and a
        # service pool small enough that the burst can actually fill
        # the bounded queue (12 workers still leaves consensus traffic
        # headroom above the 6 concurrent blocking client writes)
        "memstore_size_bytes": 384 * 1024,
        "global_memstore_limit_bytes": 4 << 20,
        "memstore_arbitration_interval_s": 0.2,
        "rpc_service_pool_threads": 12,
        "rpc_service_queue_depth": 256,
    }
    old = {f: flags.get_flag(f) for f in knobs}
    for f, v in knobs.items():
        flags.set_flag(f, v)
    cluster = MiniCluster(MiniClusterOptions(
        num_tservers=3, fs_root=str(tmp_path / "cluster"))).start()
    ctrl = None
    try:
        client = cluster.new_client()
        client.create_namespace("ovl")
        table = client.create_table("ovl", "t", SCHEMA, num_tablets=4)
        tablet_ids = cluster.wait_for_table_leaders("ovl", "t")

        # ---- phase 1: sustainable baseline (paced, comfortably under
        # capacity — the cluster serves it with zero shedding; its
        # measured ack rate anchors the 5x offered load and the 70%
        # goodput floor. Kept LOW on purpose: the storm writers must be
        # able to actually OFFER 5x this on a single CI core.)
        base_writers = _run_writers(client, table, n=2, total_rate=150,
                                    seconds=hold)
        base_acked = sum(len(w.acked) for w in base_writers)
        base_rate = base_acked / hold
        bad = [e for w in base_writers for e in w.bad]
        assert not bad, f"non-retryable errors at baseline: {bad[:3]}"
        assert base_rate > 50, f"baseline rate implausible: {base_rate}"

        # ---- phase 2: >= 5x offered load with every shedding layer on
        sampler = _Sampler(cluster)
        sampler.start()
        # pace target 9x across 8 writers: flush stalls under
        # contention eat into each writer's pace, so the target is
        # overprovisioned to keep the ACHIEVED offered rate (asserted
        # below) comfortably >= 5x
        storm = [_PacedWriter(client, table, 100 + i,
                              rate=9 * base_rate / 8)
                 for i in range(8)]
        storm_t0 = time.monotonic()
        for w in storm:
            w.start()
        # mid-storm: force the bounded-queue shed path and prove the
        # client rides it out (typed Overloaded + hint-floored retries)
        time.sleep(hold / 4)
        probe_keys = [f"w0-{i:08d}" for i in range(64)]
        burst_deadline = time.monotonic() + 60
        burst_overloaded, burst_bad = [], []
        while time.monotonic() < burst_deadline:
            ov, bd = _overflow_burst(cluster, client, table, probe_keys)
            burst_overloaded.extend(ov)
            burst_bad.extend(bd)
            if _shed_totals(cluster)["rpc_queue_overflow_total"] > 0:
                break
        time.sleep(hold / 2)
        for w in storm:
            w.stop_ev.set()
        for w in storm:
            w.join(timeout=120)
        # the burst loop's wall time varies: rate goodput over the
        # ACTUAL storm window, not the nominal hold
        storm_wall = time.monotonic() - storm_t0
        sampler.stop_ev.set()
        sampler.join(timeout=10)

        offered = sum(w.offered for w in storm)
        acked = sum(len(w.acked) for w in storm)
        goodput = acked / storm_wall
        shed = _shed_totals(cluster)
        budget = client.retry_budget

        # the storm genuinely offered >= 5x the sustainable baseline
        assert offered / storm_wall >= 5 * base_rate, (
            f"storm under-offered: {offered / storm_wall:.0f} ops/s vs "
            f"5x baseline {5 * base_rate:.0f}")
        # every rejection typed-retryable (writers + burst saw no
        # opaque errors)
        bad = [e for w in storm for e in w.bad] + burst_bad
        assert not bad, f"non-retryable errors under overload: {bad[:3]}"
        # shedding actually engaged and was COUNTED server-side
        assert shed["rpc_queue_overflow_total"] > 0, shed
        total_shed = sum(shed.values())
        assert total_shed > 0, shed
        # memstore stayed inside the tracker limit THROUGHOUT
        assert sampler.samples > 10
        assert sampler.max_memstore_ratio <= 1.0, (
            f"memstore exceeded tracker limit: "
            f"{sampler.max_memstore_ratio:.2f}x")
        # goodput under shedding >= 70% of the pre-overload rate
        assert goodput >= 0.7 * base_rate, (
            f"goodput collapsed under overload: {goodput:.0f} ops/s vs "
            f"baseline {base_rate:.0f} (offered "
            f"{offered / storm_wall:.0f})")

        # ---- phase 3: recovery within 30s of load removal
        _wait_recovered(cluster, timeout_s=30.0)
        all_acked = set()
        for w in base_writers + storm:
            all_acked |= w.acked
        _verify_acked_present(client, table, all_acked)

        # ---- phase 4: chaos cycle — PR-6 nemesis leader partition
        # under renewed overload; still zero acked loss, still heals
        ctrl = NemesisController(cluster, seed=7)
        chaos = [_PacedWriter(client, table, 200 + i,
                              rate=9 * base_rate / 8)
                 for i in range(8)]
        for w in chaos:
            w.start()
        time.sleep(1.0)
        terms_before = ctrl.capture_terms()
        ctrl.partition_leader(tablet_ids[0])
        time.sleep(min(3.0, hold / 2))
        ctrl.heal()
        time.sleep(min(3.0, hold / 2))
        for w in chaos:
            w.stop_ev.set()
        for w in chaos:
            w.join(timeout=120)
        bad = [e for w in chaos for e in w.bad]
        assert not bad, f"non-retryable errors under chaos+overload: " \
                        f"{bad[:3]}"
        ctrl.wait_all_healthy(table.table_id, timeout_s=90.0)
        ctrl.check_terms_monotonic(terms_before, ctrl.capture_terms())
        _wait_recovered(cluster, timeout_s=30.0)
        for w in chaos:
            all_acked |= w.acked
        _verify_acked_present(client, table, all_acked)
        # observability breadcrumb for the CI log
        print(f"overload soak: base={base_rate:.0f} ops/s, "
              f"goodput={goodput:.0f} ops/s, "
              f"offered={offered / storm_wall:.0f} ops/s, shed={shed}, "
              f"client_overloaded={sum(len(w.overloaded_seen) for w in storm) + len(burst_overloaded)}, "
              f"budget: spent={budget.spent_total} "
              f"exhausted={budget.exhausted_total}, "
              f"max_memstore={sampler.max_memstore_ratio:.2f}, "
              f"max_signal={sampler.max_signal_score:.2f}")
    finally:
        if ctrl is not None:
            ctrl.close()
        cluster.shutdown()
        for f, v in old.items():
            flags.set_flag(f, v)
