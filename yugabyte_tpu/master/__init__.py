"""Master: cluster catalog + tablet placement + tserver liveness.

Capability parity with src/yb/master (ref: master.h:69, catalog_manager.h:141,
sys_catalog.h:77-95, cluster_balance.cc).
"""

from yugabyte_tpu.master.master import Master, MasterOptions

__all__ = ["Master", "MasterOptions"]
