"""Distributed compaction: range-repartition + per-shard merge/GC over a mesh.

The multi-chip form of the north-star kernel. The reference parallelizes a
big compaction into key-range subcompactions, one THREAD each
(ref: rocksdb/db/compaction_job.cc:330 GenSubcompactionBoundaries, :456-468);
here each key range is one DEVICE of a `jax.sharding.Mesh`, and the data
movement that the reference does with per-thread file iterators happens as
XLA collectives over ICI:

  1. each shard samples its local route keys
  2. all_gather the samples -> identical global splitters on every shard
  3. bucket rows by destination shard; all_to_all exchanges the buckets
     (fixed per-destination capacity with all-0xFF padding rows, which sort
     to the tail and are dropped by the GC keep-mask like all padding)
  4. per-shard fused radix merge + MVCC GC (ops/merge_gc.sort_and_gc)

Routing is by the first `_W_ROUTE` 32-bit words of the DOC KEY portion of
each key (words masked to doc_key_len, zero beyond it), compared
lexicographically. Every entry of one document has identical doc-key bytes
and doc_key_len, hence an identical route key — so a document's root + column
entries and all versions of a key always land on one shard and the GC segment
logic never straddles shards. Because routing is an order-preserving prefix
of the key, shards remain globally range-partitioned: shard s's keys all
sort <= shard s+1's.

Returns per-shard sorted cols + keep/make-tombstone masks + an overflow flag
(a bucket exceeding capacity means splitters were too skewed: the caller
retries with higher capacity — compaction correctness is never silently
sacrificed).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # pre-0.4.35 jax exports it under experimental only
    from jax.experimental.shard_map import shard_map

from yugabyte_tpu.ops import merge_gc
from yugabyte_tpu.ops.merge_gc import (
    _ROW_DKL, _ROW_KEY_LEN, _ROW_WORDS, GCParams, PAD_SENTINEL, pack_cols,
    pad_template, sort_and_gc)

# Route on up to this many leading doc-key words (16 bytes). Documents whose
# doc keys share all 16 bytes route to the same bucket; the overflow retry
# absorbs the resulting skew, so this is a perf knob, not correctness.
_W_ROUTE = 4

_SAMPLES_PER_SHARD = 64


@functools.lru_cache(maxsize=64)
def dist_compact_fn(mesh: Mesh, capacity: int, is_major: bool,
                    retain_deletes: bool = False, axis: str = "shard"):
    """Build (and cache) the jitted distributed compaction step for a mesh.

    Cached per (mesh, capacity, is_major, retain_deletes, axis): rebuilding
    the shard_map closure per call would defeat the jit trace cache and
    re-lower the whole multi-collective program every compaction.

    Input cols: [R, n_total] sharded along dim 1; n_total = n_shards * n_local.
    Output: (cols_out [R, n_shards*capacity] sharded, keep, make_tombstone,
             overflow flag per shard).
    """
    n_shards = mesh.devices.size

    def per_shard(cols_local, cutoff_hi, cutoff_lo, cph, cpl):
        r, n_local = cols_local.shape
        w_route = min(_W_ROUTE, r - _ROW_WORDS)
        u32max = jnp.uint32(0xFFFFFFFF)
        is_pad_in = cols_local[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        # -- route key: doc-key words masked to doc_key_len ----------------
        # (identical across every entry/version of one document; padding
        # rows get all-0xFF route words so they route to the last shard)
        dkl = cols_local[_ROW_DKL].astype(jnp.int32)      # pad rows: -1
        words = cols_local[_ROW_WORDS:_ROW_WORDS + w_route]
        mask = merge_gc.route_word_mask(dkl, w_route)     # shared defn
        route = jnp.where(is_pad_in[None, :], u32max, words & mask)
        # -- 1/2: sample + all_gather + splitters --------------------------
        step = max(1, n_local // _SAMPLES_PER_SHARD)
        samples = route[:, ::step][:, :_SAMPLES_PER_SHARD]  # [w_route, s_loc]
        samp_pad = is_pad_in[::step][:_SAMPLES_PER_SHARD]
        g_samp = jax.lax.all_gather(samples, axis)          # [shards, w, s_loc]
        g_samp = jnp.moveaxis(g_samp, 1, 0).reshape(w_route, -1)
        g_pad = jax.lax.all_gather(samp_pad, axis).reshape(-1)
        # lex sort on the route words with the pad flag as final tiebreak,
        # so padding samples sort strictly after real ones even on 0xFF ties
        sorted_ops = jax.lax.sort(
            [g_samp[i] for i in range(w_route)] + [g_pad.astype(jnp.uint32)],
            num_keys=w_route + 1)
        # exact real-sample count (no row-count arithmetic -> no overflow)
        n_real_samples = jnp.maximum(
            g_pad.shape[0] - jnp.sum(g_pad.astype(jnp.int32)), 1)
        qs = (jnp.arange(1, n_shards) * n_real_samples) // n_shards
        splitters = [sorted_ops[i][qs] for i in range(w_route)]  # each [S-1]
        # -- 3: bucket + exchange ------------------------------------------
        # dest = number of splitters lexicographically <= route key
        lt = jnp.zeros((n_local, n_shards - 1), bool)
        eq = jnp.ones((n_local, n_shards - 1), bool)
        for i in range(w_route):
            rw, sw = route[i][:, None], splitters[i][None, :]
            lt = lt | (eq & (rw < sw))
            eq = eq & (rw == sw)
        dest = jnp.sum(~lt, axis=1)                          # [n_local]
        order = jnp.argsort(dest)                            # stable
        # input padding rows route to the LAST shard but are excluded from
        # counts so they can't trigger a spurious overflow
        real_dest = jnp.where(is_pad_in, n_shards, dest)     # bin n_shards: pad
        counts = jnp.bincount(real_dest, length=n_shards + 1)[:n_shards]
        all_counts = jnp.bincount(dest, length=n_shards)
        offsets = jnp.concatenate(
            [jnp.zeros(1, all_counts.dtype), jnp.cumsum(all_counts)[:-1]])
        overflow = jnp.any(counts > capacity)
        pos_in_group = jnp.arange(n_local) - offsets[dest[order]]
        valid = pos_in_group < capacity
        # rows past capacity go to a dump column that is sliced off before
        # the exchange — they can never clobber a real slot
        slot = jnp.where(valid, dest[order] * capacity + pos_in_group,
                         n_shards * capacity)
        # the global input index rides the exchange as one extra u32 row so
        # the host can map every surviving (shuffled, merged) row back to
        # its source slab row — output VALUES are gathered host-side from
        # exactly these indices (values never cross the mesh)
        idx_local = (jax.lax.axis_index(axis).astype(jnp.uint32)
                     * jnp.uint32(n_local)
                     + jnp.arange(n_local, dtype=jnp.uint32))
        ship = jnp.concatenate([cols_local, idx_local[None, :]], axis=0)
        pad_col = jnp.concatenate(
            [jnp.asarray(pad_template(r)), jnp.full(1, 0xFFFFFFFF,
                                                    jnp.uint32)])
        send = jnp.tile(pad_col[:, None], (1, n_shards * capacity + 1))
        send = send.at[:, slot].set(ship[:, order])
        send3 = send[:, :-1].reshape(r + 1, n_shards, capacity)
        recv = jax.lax.all_to_all(send3, axis, split_axis=1, concat_axis=1,
                                  tiled=False)
        recv = recv.reshape(r + 1, n_shards * capacity)
        cols_shard, idx_shard = recv[:r], recv[r]
        # -- 4: local fused merge + GC -------------------------------------
        perm, keep, mk = sort_and_gc(cols_shard, cutoff_hi, cutoff_lo, cph, cpl,
                                     w=r - _ROW_WORDS, is_major=is_major,
                                     retain_deletes=retain_deletes)
        out = cols_shard[:, perm]
        # padding rows are identified explicitly by the key_len sentinel
        is_pad = out[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        keep = keep & ~is_pad
        return out, keep, mk, overflow[None], idx_shard[perm]

    spec = P(None, axis)
    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(spec, P(), P(), P(), P()),
        out_specs=(spec, P(axis), P(axis), P(axis), P(axis)))
    return jax.jit(fn)


def distributed_compact(slab, params: GCParams, mesh: Mesh, axis: str = "shard",
                        capacity_factor: float = 2.0):
    """Host wrapper: pack a slab, shard it over the mesh, run the step.

    Returns (cols_out, keep, make_tombstone, src_idx) as host arrays;
    cols_out rows follow ops/merge_gc layout, in globally range-partitioned
    sorted order (shard s holds keys <= shard s+1's); src_idx[i] is the
    input slab row that produced merged position i (valid where keep/mk
    apply — padding positions carry sentinel indices and keep=False)."""
    import time as _time
    from yugabyte_tpu.utils.metrics import (record_kernel_dispatch,
                                            record_pipeline_stage)
    t0 = _time.monotonic()
    n_shards = mesh.devices.size
    cols = pack_cols(slab)[0]
    # pad the column count to a multiple of shards (pack_cols gives powers
    # of two; mesh sizes are powers of two on TPU pods)
    if cols.shape[1] % n_shards:
        extra = n_shards - (cols.shape[1] % n_shards)
        pad_block = np.tile(pad_template(cols.shape[0])[:, None], (1, extra))
        cols = np.concatenate([cols, pad_block], axis=1)
    n_local = cols.shape[1] // n_shards
    # each source sends ~n_local/n_shards rows to each destination; the
    # factor absorbs skew, with the overflow retry as the hard guard.
    # capacity is part of dist_compact_fn's lru_cache compile key, so it
    # is quantized onto the power-of-two lattice: the raw
    # rows-per-destination value varies per job and would mint a fresh
    # shard_map executable per size (a doubling retry stays on-lattice)
    cap_raw = max(64, int(n_local / n_shards * capacity_factor))
    capacity = 1 << (cap_raw - 1).bit_length()
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    fn = dist_compact_fn(mesh, capacity, params.is_major_compaction,
                         params.retain_deletes, axis)
    t_dev = _time.monotonic()
    record_pipeline_stage("host", (t_dev - t0) * 1e3)
    out, keep, mk, overflow, src_idx = fn(
        cols, jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF))
    # the chunk hand-off back to the host: kick every shard output's D2H
    # in one async wave (the overflow word decides retry first, so the
    # big buffers ride the link while the host inspects the small one)
    for a in (out, keep, mk, src_idx):
        try:
            a.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass
    if bool(np.any(np.asarray(overflow))):
        if capacity_factor >= 64:
            raise RuntimeError("distributed compaction bucket overflow at 64x")
        return distributed_compact(slab, params, mesh, axis, capacity_factor * 2)
    result = (np.asarray(out), np.asarray(keep), np.asarray(mk),
              np.asarray(src_idx).astype(np.int64))
    record_pipeline_stage("device", (_time.monotonic() - t_dev) * 1e3)
    record_kernel_dispatch("kernel_dist_compact", slab.n, cols.shape[1],
                           (_time.monotonic() - t0) * 1e3)
    return result
