"""DocRowwiseIterator: assemble rows from flattened MVCC KV pairs.

Capability parity with the reference's read path (ref:
src/yb/docdb/doc_rowwise_iterator.cc:1036 Init, src/yb/docdb/doc_reader.h:73
DocDBTableReader, src/yb/docdb/subdoc_reader.h:80). Two stages, shared by the
CPU and TPU paths:

  RESOLVE — reduce the raw (internal_key, value) stream to exactly the
  visible version of each doc path at read_ht:
    * CPU: `DocRowwiseIterator._resolve_visible` walks the merged stream of
      a DB in memcmp order — key ascending, DocHybridTime DESCENDING — so
      for each distinct doc path the FIRST version with ht <= read_ht wins;
    * TPU: the fused scan kernel (ops/scan.py) computes the same set on
      device for a whole key range at once.

  ASSEMBLE — `VisibleEntryRowAssembler` groups the resolved entries into
  rows (pure grouping; all visibility logic already happened).

Visibility rules implemented (matching docdb semantics):
  - a bare-DocKey entry (row tombstone OR object init marker) shadows every
    older subdocument write (init-marker overwrite semantics);
  - a column whose visible version is a tombstone is absent;
  - TTL: a value written at `t` with ttl expires at t + ttl — reads at or
    after the expiry treat it as absent (ref: docdb_compaction_filter.cc
    expiry rules :260-279 applied here at read time);
  - a row exists iff its liveness system column or any value column is
    visible (ref: doc_reader.cc row existence via liveness).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.common.schema import Schema
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey, split_key_and_ht
from yugabyte_tpu.docdb.doc_operations import kLivenessColumnId
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.docdb.value_type import ValueType
from yugabyte_tpu.ops.slabs import _doc_key_len


def _is_expired(value: Value, write_dht: DocHybridTime,
                read_ht: HybridTime) -> bool:
    if value.ttl_ms is None:
        return False
    expiry_micros = write_dht.ht.physical_micros + value.ttl_ms * 1000
    return read_ht.physical_micros >= expiry_micros


@dataclass
class Row:
    doc_key: DocKey
    columns: Dict[int, object]      # column id -> decoded primitive
    write_ht: HybridTime            # max HT contributing to this row

    def to_dict(self, schema: Schema) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for c, v in zip(schema.hash_columns, self.doc_key.hash_components):
            out[c.name] = v
        for c, v in zip(schema.range_columns, self.doc_key.range_components):
            out[c.name] = v
        for c in schema.value_columns:
            cid = schema.column_id(c.name)
            out[c.name] = self.columns.get(cid)
        return out


class VisibleEntryRowAssembler:
    """Group an already-MVCC-resolved entry stream into rows.

    Input entries are (key_prefix, value_bytes, ht_value) in key order with
    exactly one visible version per doc path — no tombstones, no shadowed
    history (see module docstring). Paging interface: rows(limit) +
    next_doc_key (the resume key when a limit was hit).
    """

    def __init__(self, entries, schema: Schema,
                 projection: Optional[Sequence[int]] = None):
        self._entries = entries
        self._schema = schema
        # projection entries are column IDS; column NAMES (what the RPC
        # layer carries) translate here — ONE place, so the leader read,
        # follower read and scan paths all agree. Unknown names are
        # never matched (like projecting a just-dropped column).
        if projection is not None:
            ids = set()
            for c in projection:
                if isinstance(c, str):
                    try:
                        ids.add(schema.column_id(c))
                    except KeyError:
                        pass
                else:
                    ids.add(c)
            self._projection = ids
        else:
            self._projection = None
        self.next_doc_key: Optional[bytes] = None

    def __iter__(self) -> Iterator[Row]:
        return self.rows()

    def rows(self, limit: Optional[int] = None) -> Iterator[Row]:
        cur_doc: Optional[bytes] = None
        columns: Dict[int, object] = {}
        liveness = False  # row exists: liveness marker OR any visible column,
        #                   tracked independently of the projection
        max_ht = HybridTime.kMin
        emitted = 0

        def finish() -> Optional[Row]:
            if cur_doc is None or not liveness:
                return None
            dk, _ = DocKey.decode(cur_doc)
            return Row(dk, dict(columns), max_ht)

        col_marker: Dict[int, int] = {}  # cid -> overwrite point ht

        for key, raw_value, ht_value in self._entries:
            dk_len = _doc_key_len(key)
            doc = key[:dk_len]
            if doc != cur_doc:
                row = finish()
                if row is not None:
                    yield row
                    emitted += 1
                    if limit is not None and emitted >= limit:
                        self.next_doc_key = doc
                        return
                cur_doc = doc
                columns = {}
                col_marker = {}
                liveness = False
                max_ht = HybridTime.kMin
            ht = HybridTime(ht_value)
            if ht.value > max_ht.value:
                max_ht = ht
            if not key[dk_len:]:
                liveness = True  # visible init marker
                continue
            sdk = SubDocKey.decode(key)
            if not (sdk.subkeys
                    and isinstance(sdk.subkeys[0], tuple)
                    and sdk.subkeys[0][0] == "col"):
                continue  # non-column subdocument paths: not a row part
            cid = sdk.subkeys[0][1]
            liveness = True  # any visible column proves the row exists
            if cid == kLivenessColumnId:
                continue
            if self._projection is not None and cid not in self._projection:
                continue
            if len(sdk.subkeys) == 1:
                value = Value.decode(raw_value)
                col_marker[cid] = ht_value
                if value.is_object:
                    # collection init marker: an (empty) container that
                    # OVERWRITES the older subtree at this column
                    columns[cid] = {}
                else:
                    columns[cid] = value.primitive
                continue
            # collection element ((col,cid), k, ...) — the resolve stage
            # already picked the newest visible version per exact path;
            # cross-path shadowing by the column's overwrite point
            # (replace marker or primitive) applies here
            # (ref: subdoc_reader.cc overwrite stack)
            if cid in col_marker and ht_value < col_marker[cid]:
                continue  # older than the column's replace/overwrite
            container = columns.get(cid)
            if not isinstance(container, dict):
                # no marker (merge-without-marker) or a resurrected
                # collection over an older primitive
                container = {}
                columns[cid] = container
            node = container
            for comp in sdk.subkeys[1:-1]:
                nxt = node.get(comp)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[comp] = nxt
                node = nxt
            value = Value.decode(raw_value)
            node[sdk.subkeys[-1]] = {} if value.is_object \
                else value.primitive
        row = finish()
        if row is not None:
            yield row
        self.next_doc_key = None


class DocRowwiseIterator:
    """CPU scan path: resolve MVCC inline while walking the merged stream,
    then assemble through the shared VisibleEntryRowAssembler."""

    def __init__(self, db, schema: Schema, read_ht: HybridTime,
                 lower_doc_key: bytes = b"",
                 upper_doc_key: Optional[bytes] = None,
                 projection: Optional[Sequence[int]] = None,
                 entry_stream=None):
        """entry_stream: optional pre-merged (internal_key, value) iterator
        replacing the plain DB stream — the IntentAwareIterator overlays
        provisional records this way (ref intent_aware_iterator.h)."""
        self._db = db
        self._schema = schema
        self._read_ht = read_ht
        self._lower = lower_doc_key
        self._upper = upper_doc_key
        self._entry_stream = entry_stream
        self._assembler = VisibleEntryRowAssembler(
            self._visible_stream(), schema, projection=projection)

    def _visible_stream(self):
        """RESOLVE stage: the native read engine computes visibility in C++
        when available (native/read_engine.cc mode 1 — the same semantics
        as _resolve_visible, differentially tested); Python resolves
        otherwise or when an intent overlay stream is supplied."""
        if self._entry_stream is None and hasattr(self._db, "scan_native"):
            scan = self._db.scan_native(
                lower=self._lower, upper=self._upper,
                read_ht_value=self._read_ht.value, visible=True,
                batch_rows=8192)
            if scan is not None:
                return ((k, v, ht) for k, v, ht, _w, _f, _d
                        in scan.entries())
        return self._resolve_visible()

    @property
    def next_doc_key(self) -> Optional[bytes]:
        return self._assembler.next_doc_key

    def __iter__(self) -> Iterator[Row]:
        return self.rows()

    def rows(self, limit: Optional[int] = None) -> Iterator[Row]:
        return self._assembler.rows(limit)

    def _resolve_visible(self) -> Iterator[Tuple[bytes, bytes, int]]:
        """Yield (key, value_bytes, ht_value) of exactly the visible version
        of each doc path at read_ht (the stream the TPU kernel produces on
        device for the whole range at once)."""
        read_ht = self._read_ht
        cur_doc: Optional[bytes] = None
        # Overwrite-point STACK over subpath prefixes (the same rule
        # read_subdocument and the compaction model apply): EVERY newest-
        # visible entry — bare-DocKey marker/tombstone, column value or
        # tombstone, collection replace marker — replaces the older
        # subtree at its path, so strictly-older descendants are shadowed.
        ov_stack: list = []   # [(subpath, DocHybridTime)] prefix-nested
        seen_paths: set = set()
        stream = (self._entry_stream if self._entry_stream is not None
                  else self._db.iter_from(self._lower))
        for ikey, raw_value in stream:
            prefix, dht = split_key_and_ht(ikey)
            if dht is None:
                continue
            dk_len = _doc_key_len(prefix)
            doc = prefix[:dk_len]
            if self._upper is not None and doc >= self._upper:
                break
            if doc != cur_doc:
                cur_doc = doc
                ov_stack = []
                seen_paths = set()
            if dht.ht.value > read_ht.value:
                continue  # newer than the snapshot
            subpath = prefix[dk_len:]
            if subpath in seen_paths:
                continue  # older version of an already-resolved path
            seen_paths.add(subpath)
            while ov_stack and not subpath.startswith(ov_stack[-1][0]):
                ov_stack.pop()
            value = Value.decode(raw_value)
            shadowed = any(dht < ov for _p, ov in ov_stack)
            dead = (value.is_tombstone or shadowed
                    or _is_expired(value, dht, read_ht))
            ov_stack.append((subpath, dht))
            if not dead:
                yield prefix, raw_value, dht.ht.value


def read_row(db, schema: Schema, doc_key: DocKey, read_ht: HybridTime,
             projection: Optional[Sequence[int]] = None,
             entry_stream=None) -> Optional[Row]:
    """Point row lookup (the QL read-one path)."""
    encoded = doc_key.encode()
    it = DocRowwiseIterator(db, schema, read_ht, lower_doc_key=encoded,
                            upper_doc_key=encoded + bytes([ValueType.kMaxByte]),
                            projection=projection,
                            entry_stream=entry_stream)
    for row in it:
        return row
    return None
