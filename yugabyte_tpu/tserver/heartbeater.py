"""Heartbeater: periodic TSHeartbeat from tserver to the master leader.

Capability parity with the reference (ref: src/yb/tserver/heartbeater.cc:382
`TryHeartbeat` — registration on first beat, tablet reports, master-leader
failover by re-resolving; ref master_heartbeat.proto:136,236-240). The
response piggybacks the cluster address map (server_id -> host:port) which
feeds the consensus transport resolver, plus the tserver universe view used
by clients.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from yugabyte_tpu.rpc.messenger import (
    Messenger, RemoteError, RpcTimeout, ServiceUnavailable)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils import ybsan
from yugabyte_tpu.utils.backoff import RetrySchedule
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("heartbeat_interval_ms", 200,
                  "tserver -> master heartbeat period "
                  "(ref heartbeat_interval_ms, 1000 in the reference; lower "
                  "here because MiniCluster tests drive failover timing)")

MASTER_SERVICE = "master"


@ybsan.shadow(_leader_addr=ybsan.SINGLE_WRITER)
class Heartbeater:
    def __init__(self, messenger: Messenger, master_addrs: List[str],
                 server_id: str, server_addr: str,
                 report_provider: Callable[[], List[dict]],
                 on_response: Callable[[dict], None]):
        self._messenger = messenger
        self._master_addrs = list(master_addrs)
        self._leader_addr: Optional[str] = None
        self.server_id = server_id
        self.server_addr = server_addr
        self._report_provider = report_provider
        self._on_response = on_response
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"heartbeater-{self.server_id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def heartbeat_now(self) -> bool:
        """One synchronous heartbeat attempt across known masters; returns
        True when a master leader accepted it."""
        addrs = ([self._leader_addr] if self._leader_addr else []) + [
            a for a in self._master_addrs if a != self._leader_addr]
        for addr in addrs:
            try:
                resp = self._messenger.call(
                    addr, MASTER_SERVICE, "heartbeat",
                    timeout_s=flags.get_flag("heartbeat_interval_ms") / 250.0,
                    server_id=self.server_id, server_addr=self.server_addr,
                    tablet_report=self._report_provider())
            except (RpcTimeout, ServiceUnavailable):
                continue
            except RemoteError as e:
                if e.extra.get("not_leader"):
                    # Follower master: try its hint next (ref heartbeater
                    # master-leader re-resolution).
                    hint = e.extra.get("leader_hint")
                    if hint and hint not in addrs:
                        addrs.append(hint)
                    continue
                raise
            self._leader_addr = addr
            self._on_response(resp)
            return True
        self._leader_addr = None
        return False

    def _loop(self) -> None:
        # While no master leader answers, the retry spacing grows with
        # capped exponential backoff + jitter instead of every tserver
        # hammering the dead master in lockstep at the heartbeat interval
        # (ref heartbeater.cc consecutive_failed_heartbeats_ backoff).
        interval_s = lambda: flags.get_flag("heartbeat_interval_ms") / 1000.0
        retry = RetrySchedule(initial_s=interval_s(), max_s=2.0)
        wait_s = interval_s()
        while not self._stop.wait(wait_s):
            try:
                ok = self.heartbeat_now()
            except Exception as e:  # noqa: BLE001 — keep beating
                TRACE("heartbeater %s: %r", self.server_id, e)
                ok = False
            if ok:
                retry = RetrySchedule(initial_s=interval_s(), max_s=2.0)
                wait_s = interval_s()
            else:
                wait_s = retry.record_failure()
