"""yblint (tools/analysis) test suite + tier-1 CI wiring.

Three layers:
- seeded-defect fixtures proving each pass FIRES (positive cases) and
  stays quiet on the idiomatic negatives;
- framework behavior: baseline round-trip, inline suppression, JSON
  output, pass selection;
- the CI gate: `python -m tools.analysis yugabyte_tpu/` must be clean
  against the committed baseline, and the runtime lock-order tracker
  (utils/lock_rank.py) must have observed no acquisition cycles by the
  time this module runs.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.analysis import core  # noqa: E402
from tools.analysis.passes import ALL_PASSES, passes_by_name  # noqa: E402
from tools.analysis.passes.blocking_reactor import (  # noqa: E402
    BlockingReactorPass)
from tools.analysis.passes.jit_trace_safety import (  # noqa: E402
    JitTraceSafetyPass)
from tools.analysis.passes.lock_discipline import (  # noqa: E402
    LockDisciplinePass)
from tools.analysis.passes.metric_names import MetricNamesPass  # noqa: E402
from tools.analysis.passes.swallowed_errors import (  # noqa: E402
    SwallowedErrorsPass)
from yugabyte_tpu.utils import lock_rank  # noqa: E402


def _lint(src, passes, relpath="fixture.py"):
    ctx = core.FileContext(relpath, relpath, textwrap.dedent(src))
    out = []
    for p in passes:
        out.extend(f for f in p.run(ctx)
                   if not core._is_suppressed(ctx, f))
    return out


def _codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------------------
# jit trace-safety
# ---------------------------------------------------------------------------

class TestJitTraceSafety:
    PASS = [JitTraceSafetyPass()]

    def test_host_syncs_and_branches_fire(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                if x > 0:
                    y = x.item()
                print(x)
                z = np.asarray(x)
                return float(x)
        """
        codes = _codes(_lint(src, self.PASS))
        assert codes.count("tracer-branch") == 1
        assert codes.count("host-sync") == 3   # .item(), np.asarray, float
        assert codes.count("print-tracer") == 1

    def test_static_args_and_metadata_are_negative(self):
        src = """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k", "w"))
            def f(x, k, w):
                if k > 1 and w > 4:        # statics: fine
                    x = x * 2
                if x.shape[0] > 1:         # shape metadata: fine
                    x = x + 1
                n = int(w)                 # static int(): fine
                if x is None:              # identity check: fine
                    return None
                return x
        """
        assert _lint(src, self.PASS) == []

    def test_call_site_taint_reaches_helpers(self):
        src = """
            import functools
            import jax

            _STATICS = ("m",)

            _fused = functools.partial(jax.jit, static_argnames=_STATICS)(
                lambda x, m: x)

            @functools.partial(jax.jit, static_argnames=("m",))
            def root(x, m):
                return helper(x, m)

            def helper(v, m):
                while m > 1:               # static via call site: fine
                    m //= 2
                while v > 1:               # tracer via call site: flagged
                    v = v - 1
                return v
        """
        fs = _lint(src, self.PASS)
        assert _codes(fs) == ["tracer-branch"]
        assert fs[0].symbol == "helper"

    def test_module_constant_static_argnames_resolved(self):
        src = """
            import functools
            import jax

            _STATICS = ("k", "m")

            def impl(cols, k, m):
                if k > 1:                  # static (resolved via _STATICS)
                    cols = cols * 2
                return cols

            fused = functools.partial(jax.jit, static_argnames=_STATICS)(impl)
        """
        assert _lint(src, self.PASS) == []

    def test_unhashable_static_call_site(self):
        src = """
            import jax

            @jax.jit
            def plain(x):
                return x

            def g(x, k):
                return x

            jg = jax.jit(g, static_argnames=("k",))

            def caller(a):
                return jg(a, k=[1, 2])
        """
        fs = _lint(src, self.PASS)
        assert _codes(fs) == ["unhashable-static"]

    def test_waiver(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                return x.item()  # yblint: disable=jit-trace-safety
        """
        assert _lint(src, self.PASS) == []


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    PASS = [LockDisciplinePass()]

    def test_unguarded_instance_access_fires(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []   # guarded-by: _lock

                def good(self):
                    with self._lock:
                        self._items.append(1)

                def bad(self):
                    self._items.append(2)
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1 and fs[0].symbol == "C.bad"

    def test_condition_alias_and_unlocked_suffix(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._n = 0   # guarded-by: _cv

                def via_lock(self):
                    with self._lock:       # alias of _cv: fine
                        self._n += 1

                def _bump_unlocked(self):  # caller-holds convention
                    self._n += 1
        """
        assert _lint(src, self.PASS) == []

    def test_module_global(self):
        src = """
            import threading

            _reg = {}                # guarded-by: _reg_lock
            _reg_lock = threading.Lock()

            def good():
                with _reg_lock:
                    _reg["x"] = 1

            def bad():
                return _reg.get("x")

            def shadowed(_reg):
                return _reg          # a parameter, not the global: fine
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1 and fs[0].symbol == "bad"

    def test_def_level_caller_holds_annotation(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._m = {}   # guarded-by: _lock

                def _peek(self):   # guarded-by: _lock
                    return self._m.get(1)
        """
        assert _lint(src, self.PASS) == []

    def test_multiline_assignment_annotation(self):
        src = """
            import threading
            from typing import Dict

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._m: Dict[str,
                                  int] = {}   # guarded-by: _lock

                def bad(self):
                    return self._m
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1


# ---------------------------------------------------------------------------
# blocking-call-in-reactor
# ---------------------------------------------------------------------------

class TestBlockingReactor:
    PASS = [BlockingReactorPass()]

    def test_rpc_reactor_seeds_and_reachability(self):
        src = """
            import time

            class Conn:
                def _read_loop(self):
                    while True:
                        self._handle()

                def _handle(self):
                    time.sleep(0.1)
                    f = open("/tmp/x")
                    self.done_event.wait()
        """
        fs = _lint(src, self.PASS, relpath="yugabyte_tpu/rpc/conn.py")
        assert _codes(fs) == ["reactor-file-io", "reactor-sleep",
                              "unbounded-wait"]

    def test_marker_and_bounded_negatives(self):
        src = """
            import time

            class W:
                def loop(self):   # yblint: reactor
                    self.work_queue.get(timeout=1)   # bounded: fine
                    self.done_event.wait(0.5)        # bounded: fine

                def not_reactor(self):
                    time.sleep(1)                     # off-path: fine
        """
        assert _lint(src, self.PASS, relpath="anywhere.py") == []

    def test_unbounded_queue_get(self):
        src = """
            class W:
                def _read_loop(self):
                    item = self.work_queue.get()
        """
        fs = _lint(src, self.PASS, relpath="yugabyte_tpu/rpc/w.py")
        assert _codes(fs) == ["unbounded-get"]


# ---------------------------------------------------------------------------
# migrated passes (swallowed errors / metric names) keep their behavior
# ---------------------------------------------------------------------------

class TestMigratedPasses:
    def test_swallowed_errors(self):
        src = """
            def risky():
                try:
                    work()
                except Exception:
                    pass

            def routed():
                try:
                    work()
                except Exception as e:
                    TRACE("failed: %s", e)

            def waived():
                try:
                    work()
                except Exception:  # lint: swallow-ok
                    pass

            class D:
                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass
        """
        p = SwallowedErrorsPass()
        assert p.applies_to("yugabyte_tpu/storage/db.py")
        assert not p.applies_to("yugabyte_tpu/rpc/messenger.py")
        fs = _lint(src, [p])
        assert len(fs) == 1 and fs[0].symbol == "risky"

    def test_metric_names(self):
        src = """
            e.counter('CamelCase')
            e.counter('missing_suffix')
            e.histogram('latency')
            e.gauge('depth_ok_depth')
            e.counter('waived')  # lint: metric-name-ok
            e.counter(dynamic_name)
            e.counter('fine_total')
        """
        fs = _lint(src, [MetricNamesPass()])
        assert len(fs) == 3
        assert sorted(set(_codes(fs))) == ["missing-unit-suffix",
                                           "not-snake-case"]

    def test_legacy_shims_still_answer(self, tmp_path):
        """The standalone entry points survive as shims over the passes
        (tests/test_backoff.py + tests/test_observability.py call them)."""
        sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
        try:
            import lint_metric_names
            import lint_swallowed_errors
        finally:
            sys.path.pop(0)
        bad = tmp_path / "bad.py"
        bad.write_text("e.counter('Nope')\n"
                       "try:\n    x()\nexcept Exception:\n    pass\n")
        assert len(lint_metric_names.check_file(str(bad))) == 1
        assert len(lint_swallowed_errors.check_file(str(bad))) == 1


# ---------------------------------------------------------------------------
# framework: baseline round-trip, suppression, CLI
# ---------------------------------------------------------------------------

BAD_LOCK_SRC = textwrap.dedent("""
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []   # guarded-by: _lock

        def bad(self):
            self._items.append(2)
""")


class TestFramework:
    def test_baseline_round_trip(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC)
        bl_path = str(tmp_path / "baseline.txt")

        findings = core.analyze_paths(str(tmp_path), ["mod.py"],
                                      [LockDisciplinePass()])
        assert len(findings) == 1

        # accept into the baseline -> clean run
        bl = core.Baseline.load(bl_path)
        bl.save(bl_path, findings)
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.known) == 1

        # a NEW defect still fails, the old one stays baselined
        target.write_text(BAD_LOCK_SRC
                          + "\n    def also_bad(self):\n"
                            "        return self._items\n")
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 1
        assert len(res.new) == 1 and len(res.known) == 1

        # fingerprints are line-number-free: shifting the file by a
        # comment block must not invalidate the baseline
        target.write_text("# pad\n# pad\n# pad\n" + BAD_LOCK_SRC)
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.known) == 1

        # fixing the defect leaves a STALE entry, reported but not fatal
        target.write_text(BAD_LOCK_SRC.replace(
            "self._items.append(2)",
            "with self._lock:\n            self._items.append(2)"))
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.stale) == 1

    def test_inline_suppression(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC.replace(
            "self._items.append(2)",
            "self._items.append(2)  # yblint: disable=lock-discipline"))
        findings = core.analyze_paths(str(tmp_path), ["mod.py"],
                                      [LockDisciplinePass()])
        assert findings == []

    def test_cli_json_and_pass_selection(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC)
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", str(target),
             "--no-baseline", "--json", "--passes", "lock-discipline"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert proc.returncode == 1, proc.stderr
        report = json.loads(proc.stdout)
        assert report["counts"]["new"] == 1
        assert report["new"][0]["pass"] == "lock-discipline"

    def test_unknown_pass_rejected(self):
        with pytest.raises(KeyError):
            passes_by_name(["nope"])

    def test_all_passes_have_unique_names(self):
        names = [p.name for p in ALL_PASSES]
        assert len(names) == len(set(names)) == 11

    def test_update_baseline_refuses_unjustified(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC)
        bl_path = str(tmp_path / "baseline.txt")
        findings = core.analyze_paths(str(tmp_path), ["mod.py"],
                                      [LockDisciplinePass()])
        bl = core.Baseline.load(bl_path)
        refused = bl.update(bl_path, findings)
        assert refused == [findings[0].fingerprint]
        assert not os.path.exists(bl_path)  # nothing written on refusal
        # a justified entry regenerates fine, sectioned per pass
        bl.notes[findings[0].fingerprint] = "fixture: deliberate"
        assert bl.update(bl_path, findings) == []
        text = open(bl_path).read()
        assert "# --- pass: lock-discipline ---" in text
        assert "fixture: deliberate" in text
        # and round-trips through load
        assert core.Baseline.load(bl_path).entries[
            findings[0].fingerprint] == 1

    def test_changed_scope_cli(self, tmp_path):
        """--changed exits 0 on a tree with no NEW findings (against the
        committed baseline — a dirty working tree may legitimately carry
        baselined findings in its changed files, so --no-baseline here
        would make this test depend on git state)."""
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", "--changed"],
            capture_output=True, text=True, cwd=str(tmp_path), env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# whole-program index (v2 substrate)
# ---------------------------------------------------------------------------

from tools.analysis.project_index import ProjectIndex  # noqa: E402
from tools.analysis.passes.donation_safety import (  # noqa: E402
    DonationSafetyPass)
from tools.analysis.passes.error_propagation import (  # noqa: E402
    ErrorPropagationPass)
from tools.analysis.passes.resource_lifetime import (  # noqa: E402
    ResourceLifetimePass)
from tools.analysis.passes.wire_drift import WireDriftPass  # noqa: E402


def _index_files(files):
    ctxs = [core.FileContext(rp, rp, textwrap.dedent(src))
            for rp, src in files.items()]
    return ctxs, ProjectIndex(ctxs)


def _lint_idx(files, passes, only=None):
    ctxs, idx = _index_files(files)
    out = []
    for ctx in ctxs:
        if only is not None and ctx.relpath != only:
            continue
        for p in passes:
            if not p.applies_to(ctx.relpath):
                continue
            fs = p.run(ctx, idx) if p.needs_index else p.run(ctx)
            out.extend(f for f in fs if not core._is_suppressed(ctx, f))
    return out


class TestProjectIndex:
    def test_import_aliasing(self):
        files = {
            "pkg/a.py": "def f():\n    return 1\n",
            "pkg/b.py": ("from pkg.a import f as g\n"
                         "import pkg.a as mod\n\n"
                         "def h():\n    return g() + mod.f()\n"),
        }
        _, idx = _index_files(files)
        mi = idx.by_relpath["pkg/b.py"]
        assert idx.resolve(mi, "g") == "pkg.a.f"
        assert idx.resolve(mi, "mod.f") == "pkg.a.f"
        assert idx.call_graph["pkg.b.h"] == {"pkg.a.f"}

    def test_relative_imports(self):
        files = {
            "pkg/__init__.py": "",
            "pkg/top.py": "def ft():\n    pass\n",
            "pkg/sub/__init__.py": "",
            "pkg/sub/x.py": "def fx():\n    pass\n",
            "pkg/sub/y.py": ("from .x import fx\n"
                             "from ..top import ft as t\n\n"
                             "def fy():\n    fx()\n    t()\n"),
        }
        _, idx = _index_files(files)
        assert idx.call_graph["pkg.sub.y.fy"] == {"pkg.sub.x.fx",
                                                  "pkg.top.ft"}

    def test_method_resolution_through_self(self):
        files = {"pkg/c.py": """
            class Base:
                def shared(self):
                    return 1

            class D(Base):
                def run(self):
                    return self.shared() + self.local()

                def local(self):
                    return 2
        """}
        _, idx = _index_files(files)
        assert idx.call_graph["pkg.c.D.run"] == {"pkg.c.Base.shared",
                                                 "pkg.c.D.local"}

    def test_attr_types_and_typed_receivers(self):
        files = {"pkg/d.py": """
            class Widget:
                def spin(self):
                    return 1

            def make_widget() -> Widget:
                return Widget()

            class Owner:
                def __init__(self, w: Widget):
                    self.w = w
                    self.made = make_widget()

                def go(self):
                    return self.w.spin() + self.made.spin()
        """}
        _, idx = _index_files(files)
        owner = idx.classes["pkg.d.Owner"]
        assert owner.attr_types == {"w": "pkg.d.Widget",
                                    "made": "pkg.d.Widget"}
        assert "pkg.d.Widget.spin" in idx.call_graph["pkg.d.Owner.go"]

    def test_callback_reference_edge(self):
        files = {"pkg/e.py": """
            import threading

            def job():
                def worker():
                    inner()
                t = threading.Thread(target=worker)
                t.start()

            def inner():
                pass
        """}
        _, idx = _index_files(files)
        assert "pkg.e.job.worker" in idx.call_graph["pkg.e.job"]
        assert "pkg.e.inner" in idx.call_graph["pkg.e.job.worker"]
        assert idx.reachable(["pkg.e.job"]) >= {"pkg.e.job",
                                                "pkg.e.job.worker",
                                                "pkg.e.inner"}


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------

DONATION_PRELUDE = """
    import functools
    import jax

    def _impl(cols, n):
        return cols

    fused = functools.partial(jax.jit, donate_argnums=(0,))(_impl)
"""


class TestDonationSafety:
    PASS = [DonationSafetyPass()]

    def _lint(self, body):
        src = textwrap.dedent(DONATION_PRELUDE) + textwrap.dedent(body)
        return _lint_idx({"yugabyte_tpu/fake/k.py": src}, self.PASS)

    def test_use_after_donate_fires(self):
        fs = self._lint("""
            def bad(arr):
                out = fused(arr, 4)
                return arr + out
        """)
        assert _codes(fs) == ["use-after-donate"]
        assert fs[0].symbol == "bad"

    def test_redispatch_counts_as_use(self):
        fs = self._lint("""
            def bad(arr):
                a = fused(arr, 4)
                b = fused(arr, 4)
                return a, b
        """)
        assert _codes(fs) == ["use-after-donate"]

    def test_rebind_clears_and_metadata_is_fine(self):
        fs = self._lint("""
            def fine(arr, staged):
                out = fused(staged.cols, 4)
                n = staged.n           # other attrs stay legal
                arr = fused(arr, 4)    # rebind: arr now holds the result
                return arr, out, n
        """)
        assert fs == []

    def test_root_escape_fires_and_conditional_poison_clears(self):
        fs = self._lint("""
            def escapes(staged):
                packed = fused(staged.cols, 4)
                return Handle(packed, staged)
        """)
        assert _codes(fs) == ["escape-after-donate"]
        fs = self._lint("""
            def guarded(staged, donate):
                fn = fused if donate else _impl
                packed = fn(staged.cols, 4)
                if donate:
                    staged = replace(staged, cols=None)
                return Handle(packed, staged)
        """)
        assert fs == []

    def test_helper_one_level(self):
        fs = self._lint("""
            def launch(staged):
                return fused(staged.cols, 4)

            def caller(s):
                h = launch(s)
                return s.cols
        """)
        assert _codes(fs) == ["use-after-donate"]
        assert fs[0].symbol == "caller"

    def test_suppression(self):
        fs = self._lint("""
            def waived(arr):
                out = fused(arr, 4)
                return arr + out  # yblint: disable=donation-safety
        """)
        assert fs == []

    def test_chained_buffer_handoff_read_fires(self):
        """The device-resident chain's handoff shape: a handle's keep
        mask donated to the survivor scan must never be read again —
        a later gather through the same attribute sees reused HBM."""
        fs = self._lint("""
            def bad(handle):
                pos = fused(handle.keep, 4)
                return pos, handle.keep
        """)
        assert _codes(fs) == ["use-after-donate"]
        assert fs[0].symbol == "bad"

    def test_chained_buffer_handoff_poison_clears(self):
        """The production pattern (run_merge.survivor_positions): donate
        under a capability guard, then poison the handle's attribute so
        late readers fail loudly — the attribute rebind clears the taint
        and the conditional donation merges clean."""
        fs = self._lint("""
            def good(handle, donate):
                keep = handle.keep
                if donate:
                    pos = fused(keep, 4)
                    handle.keep = None   # poison: late readers fail loudly
                else:
                    pos = _impl(keep, 4)
                return pos
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# error propagation
# ---------------------------------------------------------------------------

class TestErrorPropagation:
    PASS = [ErrorPropagationPass()]

    def _lint(self, body, relpath="yugabyte_tpu/storage/fake.py"):
        return _lint_idx({relpath: body}, self.PASS)

    def test_unrouted_handler_on_flush_path_fires(self):
        fs = self._lint("""
            def flush_units():
                helper()
                try:
                    io()
                except ValueError:
                    recover()

            def helper():
                try:
                    io()
                except OSError:
                    fallback()

            def unrelated():
                try:
                    io()
                except OSError:
                    fallback()
        """)
        assert _codes(fs) == ["unrouted-except", "unrouted-except"]
        assert sorted(f.symbol for f in fs) == ["flush_units", "helper"]

    def test_worker_closure_on_path_is_covered(self):
        fs = self._lint("""
            import threading

            def run_compaction():
                def ingest():
                    try:
                        io()
                    except OSError:
                        fallback()
                t = threading.Thread(target=ingest)
                t.start()
        """)
        assert _codes(fs) == ["unrouted-except"]
        assert fs[0].symbol == "run_compaction.ingest"

    def test_routing_raise_trace_and_marker_are_clean(self):
        fs = self._lint("""
            def flush_ok():
                try:
                    io()
                except OSError as e:
                    TRACE("failed: %s", e)
                try:
                    io()
                except OSError:
                    raise
                try:
                    io()
                except OSError:  # yblint: contained(fixture: safe)
                    fallback()
                try:
                    io()
                except OSError as e:
                    self._set_background_error("flush", e)
        """)
        assert fs == []

    def test_outside_critical_dirs_not_reported(self):
        fs = self._lint("""
            def flush_units():
                try:
                    io()
                except OSError:
                    fallback()
        """, relpath="yugabyte_tpu/yql/fake.py")
        assert fs == []

    def test_client_dir_is_reported(self):
        """PR 11 seed extension: the client batcher joined the report
        set — a swallowed send error in flush turns an unacked batch
        into a silently 'acked' one."""
        fs = self._lint("""
            def flush_units():
                try:
                    io()
                except OSError:
                    fallback()
        """, relpath="yugabyte_tpu/client/fake.py")
        assert len(fs) == 1

    def test_nemesis_and_cancel_paths_are_seeded(self):
        """PR 6 seed extension: chaos/nemesis fault-injection and
        pipeline-cancellation paths must route or justify containment —
        a swallowed error in fault injection makes chaos tests pass
        vacuously."""
        fs = self._lint("""
            def apply_nemesis_window():
                try:
                    inject()
                except OSError:
                    fallback()

            def cancel_background_work():
                try:
                    abort()
                except ValueError:
                    fallback()
        """, relpath="yugabyte_tpu/rpc/fake.py")
        assert _codes(fs) == ["unrouted-except", "unrouted-except"]
        assert sorted(f.symbol for f in fs) == [
            "apply_nemesis_window", "cancel_background_work"]

    def test_nemesis_module_functions_all_seeded(self):
        """Every function of rpc/nemesis.py (and integration/chaos.py)
        is a seed, mirroring the WAL-module rule."""
        fs = _lint_idx({"yugabyte_tpu/rpc/nemesis.py": (
            "def check_link(src, dst):\n"
            "    try:\n"
            "        fire()\n"
            "    except OSError:\n"
            "        fallback()\n")}, self.PASS)
        assert _codes(fs) == ["unrouted-except"]


# ---------------------------------------------------------------------------
# resource lifetime
# ---------------------------------------------------------------------------

class TestResourceLifetime:
    PASS = [ResourceLifetimePass()]

    def _lint(self, body):
        return _lint_idx({"yugabyte_tpu/fake/r.py": body}, self.PASS)

    def test_lease_unreleased_and_unsafe(self):
        fs = self._lint("""
            def leaky(pool):
                arr = pool.acquire((4, 4))
                work(arr)

            def risky(pool):
                arr = pool.acquire((4, 4))
                work(arr)
                pool.release(arr)
        """)
        assert _codes(fs) == ["leak-on-exception", "unreleased"]

    def test_lease_exception_safe_forms(self):
        fs = self._lint("""
            def fin(pool):
                arr = pool.acquire((4, 4))
                try:
                    work(arr)
                finally:
                    pool.release(arr)

            def mirrored(pool):
                arr = pool.acquire((4, 4))
                try:
                    work(arr)
                except Exception:
                    pool.release(arr)
                    raise
                upload(arr)
                pool.release(arr)

            def handed_off(pool, sink):
                arr = pool.acquire((4, 4))
                sink.slot = arr
        """)
        assert fs == []

    def test_file_handles(self):
        fs = self._lint("""
            def leak(env):
                f = env.open_append("x")
                f.append(b"d")
                f.close()

            def ok(env):
                f = env.open_append("x")
                try:
                    f.append(b"d")
                finally:
                    f.close()

            def ok_with(path):
                with open(path) as f:
                    return f.read()
        """)
        assert _codes(fs) == ["leak-on-exception"]
        assert fs[0].symbol == "leak"

    def test_raw_lock_acquire(self):
        fs = self._lint("""
            def raw(self):
                self._lock.acquire()
                do()
                self._lock.release()

            def raw_ok(self):
                self._lock.acquire()
                try:
                    do()
                finally:
                    self._lock.release()
        """)
        assert _codes(fs) == ["raw-lock-acquire"]
        assert fs[0].symbol == "raw"

    def test_suppression(self):
        fs = self._lint("""
            def transfer(pool):
                arr = pool.acquire((4, 4))  # yblint: disable=resource-lifetime
                work(arr)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# wire drift
# ---------------------------------------------------------------------------

WIRE_SERVER = """
    SVC = "fakesvc"

    class Handler:
        def ping(self, token, extra=None):
            return {"ok": True, "token": token}

        def opaque(self, token):
            return make_response(token)

    def setup(messenger):
        h = Handler()
        messenger.register_service(SVC, h)
"""


class TestWireDrift:
    PASS = [WireDriftPass()]

    def _lint(self, client_src, server_src=WIRE_SERVER):
        return _lint_idx(
            {"yugabyte_tpu/fake/server.py": server_src,
             "yugabyte_tpu/fake/client.py": client_src},
            self.PASS, only="yugabyte_tpu/fake/client.py")

    def test_clean_site(self):
        assert self._lint("""
            from yugabyte_tpu.fake.server import SVC

            def good(messenger, addr):
                resp = messenger.call(addr, SVC, "ping", token=1)
                return resp["ok"], resp.get("token")
        """) == []

    def test_request_field_drift(self):
        fs = self._lint("""
            from yugabyte_tpu.fake.server import SVC

            def bad(messenger, addr):
                return messenger.call(addr, SVC, "ping", tok=1)
        """)
        assert _codes(fs) == ["missing-request-field",
                              "unknown-request-field"]

    def test_unknown_method_and_drifted_response(self):
        fs = self._lint("""
            from yugabyte_tpu.fake.server import SVC

            def bad_method(messenger, addr):
                return messenger.call(addr, SVC, "nope")

            def bad_resp(messenger, addr):
                resp = messenger.call(addr, SVC, "ping", token=1)
                return resp["okk"]

            def opaque_resp_not_checked(messenger, addr):
                resp = messenger.call(addr, SVC, "opaque", token=1)
                return resp["whatever"]
        """)
        assert _codes(fs) == ["drifted-response-field", "unknown-method"]

    def test_wrapper_dispatch(self):
        fs = self._lint("""
            from yugabyte_tpu.fake.server import SVC

            class Client:
                def _rpc(self, mth, **kw):
                    return self._messenger.call("a", SVC, mth, **kw)

                def do(self):
                    return self._rpc("ping", token=1, bogus=2)
        """)
        assert _codes(fs) == ["unknown-request-field"]

    def test_codec_pair_drift(self):
        fs = _lint_idx({"yugabyte_tpu/fake/wire.py": """
            def thing_to_wire(t):
                return {"a": t.a, "b": t.b}

            def thing_from_wire(w):
                return Thing(a=w["a"], c=w["c"])

            def ok_to_wire(t):
                w = {"x": t.x}
                if t.y:
                    w["y"] = t.y
                return w

            def ok_from_wire(w):
                return Thing(x=w["x"], y=w.get("y"))
        """}, self.PASS)
        assert _codes(fs) == ["wire-field-never-read",
                              "wire-field-never-written"]

    def test_declared_pair(self):
        fs = _lint_idx(
            {"yugabyte_tpu/fake/prod.py": """
                def make(self):  # yblint: wire-pair(tp, writes)
                    return [{"x": 1, "y": 2}]
             """,
             "yugabyte_tpu/fake/cons.py": """
                def take(self, report):  # yblint: wire-pair(tp, reads)
                    return [r["x"] for r in report]
             """},
            self.PASS, only="yugabyte_tpu/fake/prod.py")
        assert _codes(fs) == ["wire-field-never-read"]
        assert "'y'" in fs[0].message


# ---------------------------------------------------------------------------
# runtime lock-order tracker
# ---------------------------------------------------------------------------

class TestLockRank:
    def test_cycle_detection_unit(self):
        lock_rank.reset()
        try:
            a = lock_rank.TrackedLock(threading.Lock(), "test.A")
            b = lock_rank.TrackedLock(threading.Lock(), "test.B")
            c = lock_rank.TrackedLock(threading.Lock(), "test.C")
            with a:
                with b:
                    pass
            with b:
                with c:
                    pass
            assert lock_rank.find_cycle() is None
            with c:
                with a:   # closes A -> B -> C -> A
                    pass
            cycle = lock_rank.find_cycle()
            assert cycle is not None
            assert lock_rank.violations(), "cycle must be latched"
            with pytest.raises(AssertionError):
                lock_rank.assert_no_cycles()
        finally:
            lock_rank.reset()

    def test_enabled_under_pytest_and_noop_probe(self):
        assert lock_rank.enabled()   # pytest is in sys.modules here
        raw = threading.Lock()
        t = lock_rank.tracked(raw, "test.probe")
        assert isinstance(t, lock_rank.TrackedLock)
        # non-blocking probe failures record nothing
        with t:
            held_before = list(lock_rank._held_stack())
            assert not t.acquire(blocking=False)
            assert lock_rank._held_stack() == held_before

    def test_condition_over_tracked_lock(self):
        inner = lock_rank.tracked(threading.Lock(), "test.cv_lock")
        cv = threading.Condition(inner)
        done = []

        def waiter():
            with cv:
                cv.wait(timeout=2.0)
                done.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.05)
        with cv:
            cv.notify()
        t.join(timeout=5)
        assert done == [1]


# ---------------------------------------------------------------------------
# kernel-contracts
# ---------------------------------------------------------------------------

class TestKernelContracts:
    def _pass(self):
        from tools.analysis.passes.kernel_contracts import (
            KernelContractsPass)
        return [KernelContractsPass()]

    def _lint(self, src, relpath="pkg/fix.py"):
        return _lint_idx({relpath: src}, self._pass())

    def test_weak_scalar_operand(self):
        src = """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("w",))
        def kern(x, lo, w):
            return x * lo

        def call_bad(x):
            return kern(x, 3, w=4)

        def call_good(x):
            return kern(x, jnp.uint32(3), w=4)
        """
        fs = self._lint(src)
        assert _codes(fs) == ["weak-scalar-operand"]
        assert fs[0].symbol == "call_bad"

    def test_unhashable_static_cross_module(self):
        files = {
            "pkg/kern.py": textwrap.dedent("""
                import functools
                import jax

                @functools.partial(jax.jit, static_argnames=("cfg",))
                def kern(x, cfg):
                    return x
            """),
            "pkg/caller.py": textwrap.dedent("""
                from pkg.kern import kern

                def call_bad(x):
                    return kern(x, cfg=[1, 2])

                def call_good(x):
                    return kern(x, cfg=(1, 2))
            """),
        }
        fs = _lint_idx(files, self._pass(), only="pkg/caller.py")
        assert _codes(fs) == ["unhashable-static"]

    def test_jit_in_loop_and_per_call(self):
        src = """
        import functools
        import jax

        def per_call(f):
            return jax.jit(f)

        def loopy(fs):
            out = []
            for f in fs:
                out.append(jax.jit(f))
            return out

        @functools.lru_cache(maxsize=8)
        def builder(n):
            return jax.jit(lambda x: x * n)

        w = jax.jit(per_call)
        """
        fs = self._lint(src)
        assert _codes(fs) == ["jit-in-loop", "jit-per-call"]

    def test_captured_host_array(self):
        src = """
        import jax
        import jax.numpy as jnp
        import numpy as np

        TABLE = np.arange(1024)
        SCALAR = np.uint32(7)

        @jax.jit
        def kern_bad(x):
            return x + jnp.asarray(TABLE)

        @jax.jit
        def kern_good(x, table):
            return x + table + jnp.uint32(SCALAR)
        """
        fs = self._lint(src)
        assert _codes(fs) == ["captured-host-array"]
        assert fs[0].symbol == "kern_bad"

    def test_unquantized_static_and_lattice_negatives(self):
        src = """
        import functools
        import jax
        from yugabyte_tpu.ops.run_merge import run_bucket

        @functools.partial(jax.jit, static_argnames=("m", "w"))
        def kern(x, m, w):
            return x

        def bad(x):
            m = x.shape[1] // 2
            return kern(x, m=m, w=4)

        def good_quantizer(x):
            m = run_bucket(x.shape[1])
            return kern(x, m=m, w=4)

        def good_attrs(x, staged):
            return kern(x, m=staged.m, w=staged.w)

        def good_pow2(x, n):
            return kern(x, m=1 << (n - 1).bit_length(), w=4)
        """
        fs = self._lint(src)
        assert _codes(fs) == ["unquantized-static"]
        assert fs[0].symbol == "bad"

    def test_lru_cache_factory_params_are_compile_keys(self):
        src = """
        import functools
        import jax

        @functools.lru_cache(maxsize=8)
        def build(capacity, is_major):
            return jax.jit(lambda x: x[:capacity])

        def call_bad(cols, k):
            capacity = cols.shape[1] // k
            return build(capacity, True)

        def call_good(cols, k):
            capacity = 1 << (cols.shape[1] // k - 1).bit_length()
            return build(capacity, True)
        """
        fs = self._lint(src)
        assert _codes(fs) == ["unquantized-static"]
        assert fs[0].symbol == "call_bad"

    def test_suppression(self):
        src = """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("w",))
        def kern(x, lo, w):
            return x

        def call(x):
            return kern(x, 3, w=4)  # yblint: disable=kernel-contracts
        """
        assert self._lint(src) == []

    # --------------------------------------------- coverage cross-checks
    def _synthetic_manifest(self, prewarmed=False, qkey=None):
        return {"families": {"run_merge_fused": {"entries": [{
            "key": "k_pad=2 m=512 n_cmp=8 w=4 impl=lexsort",
            "bucket": {"k_pad": 2, "m": 512, "w": 4, "n_cmp": 8},
            "prewarmed": prewarmed,
            "quarantine_key": qkey if qkey is not None else [2, 512],
        }]}}}

    def test_prewarm_coverage_gap_fixture(self):
        from tools.analysis.passes.kernel_contracts import (
            coverage_problems)
        probs = coverage_problems(self._synthetic_manifest(
            prewarmed=False), prewarm_shapes=((4, 1024, 4, 8),))
        codes = {c for c, _, _ in probs}
        assert codes == {"unwarmed-bucket", "overwarmed-bucket"}
        # tokens are stable per-bucket fingerprints (baseline-able)
        tokens = {t for _, t, _ in probs}
        assert "run_merge_fused k_pad=2 m=512 n_cmp=8 w=4 impl=lexsort" \
            in tokens

    def test_prewarm_coverage_clean_fixture(self):
        from tools.analysis.passes.kernel_contracts import (
            coverage_problems)
        probs = coverage_problems(self._synthetic_manifest(
            prewarmed=True), prewarm_shapes=((2, 512, 4, 8),))
        assert probs == []

    def test_policy_key_mismatch_fixture(self):
        from tools.analysis.passes.kernel_contracts import (
            coverage_problems)
        probs = coverage_problems(self._synthetic_manifest(
            prewarmed=True, qkey=[4, 512]))
        assert [c for c, _, _ in probs] == ["policy-key-mismatch"]

    # ------------------------------- pushdown-family (PR 13) fixtures
    def _scan_pushdown_manifest(self, prewarmed=True, qkey=None):
        return {"families": {"scan_agg": {"entries": [{
            "key": "scan_agg c_pad=1 n_pad=65536 p_pad=1 w=4 "
                   "impl=vals-presorted",
            "bucket": {"c_pad": 1, "n_pad": 65536, "p_pad": 1, "w": 4},
            "prewarmed": prewarmed,
            "quarantine_key": qkey if qkey is not None else [1, 65536],
        }]}}}

    def test_scan_pushdown_unwarmed_fixture(self):
        from tools.analysis.passes.kernel_contracts import (
            coverage_problems)
        probs = coverage_problems(
            self._scan_pushdown_manifest(prewarmed=False))
        codes = [c for c, _, _ in probs]
        assert codes == ["unwarmed-bucket"]
        assert probs[0][1].startswith("scan_agg ")

    def test_scan_pushdown_clean_fixture(self):
        from tools.analysis.passes.kernel_contracts import (
            coverage_problems)
        assert coverage_problems(self._scan_pushdown_manifest()) == []

    def test_committed_manifest_declares_pushdown_families(self):
        """The committed manifest carries the scan_filtered/scan_agg
        lattices with prewarmed entries whose quarantine keys speak the
        (1, n_pad) vocabulary of offload_policy.point_read_bucket_key —
        the same keys the runtime fault containment parks."""
        from tools.analysis.kernel_manifest import load_manifest
        from yugabyte_tpu.storage.offload_policy import (
            point_read_bucket_key)
        m = load_manifest()
        assert m is not None
        for fam in ("scan_filtered", "scan_agg"):
            rec = m["families"][fam]
            entries = [e for e in rec["entries"]
                       if e.get("quarantine_key")]
            assert entries, fam
            assert any(e["prewarmed"] for e in entries), fam
            for e in entries:
                n_pad = e["bucket"]["n_pad"]
                assert tuple(e["quarantine_key"]) \
                    == point_read_bucket_key(n_pad), e["key"]

    def test_manifest_drift_reported_as_finding(self, tmp_path):
        """The pass turns a committed-JSON drift into a finding anchored
        at ops/run_merge.py (the tier-1 gate path)."""
        from tools.analysis.passes.kernel_contracts import (
            KernelContractsPass)
        bad = tmp_path / "kernel_manifest.json"
        bad.write_text(json.dumps({"families": {}}))
        p = KernelContractsPass(manifest_path=str(bad))
        src = "X = 1\n"
        ctx = core.FileContext("yugabyte_tpu/ops/run_merge.py",
                               "yugabyte_tpu/ops/run_merge.py", src)
        fs = p.run(ctx)
        assert any(f.code == "family-missing" for f in fs)
        # ... and a missing manifest file is its own finding
        p2 = KernelContractsPass(manifest_path=str(tmp_path / "nope.json"))
        fs2 = p2.run(ctx)
        assert [f.code for f in fs2] == ["manifest-missing"]


# ---------------------------------------------------------------------------
# CI gates (tier-1): repo is yblint-clean; no lock-order cycles observed
# ---------------------------------------------------------------------------

def test_repo_is_yblint_clean():
    """The tier-1 gate: the full analyzer over yugabyte_tpu/ must report
    no findings beyond the committed baseline (and the baseline itself
    must not rot: stale entries are tolerated here but reported by the
    CLI so they get pruned)."""
    res = core.run_analysis()
    assert not res.new, "\n".join(f.render() for f in res.new)


def test_repo_baseline_is_empty():
    """Acceptance: the final tree needs no suppressions — every entry
    added to the baseline must carry a per-line justification, and today
    there are none."""
    bl = core.Baseline.load(core.DEFAULT_BASELINE)
    unjustified = [fp for fp in bl.entries if fp not in bl.notes]
    assert not unjustified, (
        "baseline entries without a justification: "
        + "\n".join(unjustified))


def test_no_lock_order_cycles_observed():
    """Every MiniCluster/raft/WAL/device-cache lock acquired anywhere in
    this pytest process runs through utils/lock_rank.py; by the time this
    module executes, the recorded acquisition graph must be acyclic."""
    lock_rank.assert_no_cycles()


# ---------------------------------------------------------------------------
# ybsan-coverage
# ---------------------------------------------------------------------------

class TestYbsanCoverage:
    def PASS(self):
        from tools.analysis.passes.ybsan_coverage import YbsanCoveragePass
        return [YbsanCoveragePass()]

    def _run(self, src):
        return _lint(src, self.PASS(), relpath="yugabyte_tpu/fixture.py")

    def test_thread_spawner_without_optin_flagged(self):
        out = self._run("""
            import threading

            class Spawner:
                def __init__(self):
                    self.state = {}
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
        """)
        assert _codes(out) == ["unsanitized-shared-state"]

    def test_pool_submit_without_optin_flagged(self):
        out = self._run("""
            class Submitter:
                def __init__(self, pool):
                    self.jobs = []
                    pool.submit(self._work)
        """)
        assert _codes(out) == ["unsanitized-shared-state"]

    def test_guarded_by_annotation_satisfies(self):
        out = self._run("""
            import threading

            class Guarded:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = {}   # guarded-by: _lock
                    self._t = threading.Thread(target=self._run)
        """)
        assert out == []

    def test_shadow_decorator_satisfies(self):
        out = self._run("""
            import threading
            from yugabyte_tpu.utils import ybsan

            @ybsan.shadow(state=ybsan.SINGLE_WRITER)
            class Shadowed:
                def __init__(self):
                    self.state = 0
                    self._t = threading.Thread(target=self._run)
        """)
        assert out == []

    def test_class_line_suppression(self):
        out = self._run("""
            import threading

            class Confined:  # yblint: disable=ybsan-coverage — immutable payload handoff only
                def __init__(self):
                    self._t = threading.Thread(target=print)
        """)
        assert out == []

    def test_non_concurrent_class_clean(self):
        out = self._run("""
            class Plain:
                def __init__(self):
                    self.state = {}
        """)
        assert out == []

    def test_outside_package_not_applicable(self):
        p = self.PASS()[0]
        assert p.applies_to("yugabyte_tpu/storage/db.py")
        assert not p.applies_to("tools/fixture.py")
        assert not p.applies_to("tests/test_storage.py")
