"""MemTracker tree + TabletMemoryManager arbitration.

Covers the reference semantics: consumption propagates to ancestors
(mem_tracker.h:87-98), TryConsume enforces every limit on the chain and
invokes GarbageCollectors before rejecting (mem_tracker.cc LimitExceeded),
soft-limit backpressure (mem_tracker.cc:557), and the global-memstore
arbiter flushing the tablet with the oldest mutable write
(tablet_memory_manager.cc:214-283).
"""

import time

import pytest

from yugabyte_tpu.utils.mem_tracker import (
    MemTracker, ScopedTrackedConsumption, reset_root_for_tests, root_tracker)
from yugabyte_tpu.tserver.tablet_memory_manager import TabletMemoryManager
from yugabyte_tpu.utils import flags


# --------------------------------------------------------------- MemTracker

def test_consumption_propagates_to_ancestors():
    root = MemTracker(0, "r")
    mid = MemTracker(0, "m", parent=root)
    leaf = MemTracker(0, "l", parent=mid)
    leaf.consume(100)
    mid.consume(50)
    assert leaf.consumption() == 100
    assert mid.consumption() == 150
    assert root.consumption() == 150
    leaf.release(40)
    assert root.consumption() == 110
    assert leaf.peak_consumption() == 100


def test_try_consume_enforces_chain_limits():
    root = MemTracker(1000, "r")
    child = MemTracker(0, "c", parent=root)   # unlimited child
    assert child.try_consume(900)
    # child has no limit, but the parent's 1000 still binds
    assert not child.try_consume(200)
    assert child.consumption() == 900
    assert child.try_consume(100)
    assert root.consumption() == 1000


def test_gc_invoked_before_rejection():
    cache = {"used": 800}
    tracker = MemTracker(1000, "cache",
                         consumption_fn=lambda: cache["used"])

    def gc(required):
        cache["used"] = max(0, cache["used"] - max(required, 500))

    tracker.add_gc_function(gc)
    # 800 + 300 > 1000 -> GC frees, then fits
    assert tracker.try_consume(300)
    assert cache["used"] <= 700


def test_soft_limit():
    old = flags.get_flag("memory_limit_soft_percentage")
    flags.set_flag("memory_limit_soft_percentage", 85)
    try:
        t = MemTracker(1000, "t")
        t.consume(800)
        r = t.soft_limit_exceeded()
        assert not r.exceeded and r.current_capacity_pct == pytest.approx(0.8)
        t.consume(100)
        assert t.soft_limit_exceeded().exceeded
    finally:
        flags.set_flag("memory_limit_soft_percentage", old)


def test_scoped_consumption_and_unregister():
    root = MemTracker(0, "r")
    child = root.find_or_create_child("c")
    with ScopedTrackedConsumption(child, 64):
        assert root.consumption() == 64
    assert root.consumption() == 0
    child.consume(10)
    child.unregister_from_parent()
    assert root.consumption() == 0          # subtree tally released
    assert root.find_child("c") is None
    # a new same-id child may now be created (ref mem_tracker.h:100-105)
    again = root.find_or_create_child("c")
    assert again is not child


def test_root_tracker_reads_rss():
    reset_root_for_tests()
    r = root_tracker()
    assert r.consumption() > 0              # live process RSS
    assert r.limit > 0
    assert root_tracker() is r
    sub = r.find_or_create_child("x")
    assert "x" in r.log_usage()
    j = r.tree_json()
    assert any(c["id"] == "x" for c in j["children"])
    sub.unregister_from_parent()


# ------------------------------------------------------ TabletMemoryManager

class FakeTablet:
    def __init__(self, tablet_id, nbytes, first_write_s):
        self.tablet_id = tablet_id
        self._bytes = nbytes
        self._first = first_write_s
        self.flushes = 0

    def memstore_bytes(self):
        return self._bytes

    def oldest_memstore_write_s(self):
        return self._first if self._bytes else None

    def flush(self):
        self.flushes += 1
        self._bytes = 0
        self._first = None


class FakePeer:
    def __init__(self, tablet):
        self.tablet = tablet


def _mgr(peers, **kw):
    root = MemTracker(1 << 40, "test_root")
    return TabletMemoryManager(lambda: peers, server_tracker=root,
                               server_id="t0", **kw)


def test_arbiter_flushes_oldest_first():
    now = time.monotonic()
    old = FakeTablet("old", 600, now - 10)
    new = FakeTablet("new", 600, now)
    peers = [FakePeer(new), FakePeer(old)]
    flags.set_flag("global_memstore_limit_bytes", 1000)
    try:
        m = _mgr(peers)
        seen = []
        m.flush_listeners.append(seen.append)
        flushed = m.flush_tablet_if_limit_exceeded()
        # 1200 > 1000: one flush (the OLDEST) brings it to 600 <= 1000
        assert flushed == 1
        assert old.flushes == 1 and new.flushes == 0
        assert seen == ["old"]
    finally:
        flags.set_flag("global_memstore_limit_bytes", 0)


def test_arbiter_noop_under_limit():
    t = FakeTablet("a", 10, time.monotonic())
    flags.set_flag("global_memstore_limit_bytes", 1000)
    try:
        m = _mgr([FakePeer(t)])
        assert m.flush_tablet_if_limit_exceeded() == 0
        assert t.flushes == 0
    finally:
        flags.set_flag("global_memstore_limit_bytes", 0)


def test_arbiter_flushes_until_under_limit():
    now = time.monotonic()
    tablets = [FakeTablet(f"t{i}", 500, now + i) for i in range(4)]
    flags.set_flag("global_memstore_limit_bytes", 900)
    try:
        m = _mgr([FakePeer(t) for t in tablets])
        flushed = m.flush_tablet_if_limit_exceeded()
        # 2000 -> flush t0 (1500) -> t1 (1000) -> t2 (500 <= 900): 3 flushes
        assert flushed == 3
        assert [t.flushes for t in tablets] == [1, 1, 1, 0]
    finally:
        flags.set_flag("global_memstore_limit_bytes", 0)


def test_block_cache_gc_registered():
    from yugabyte_tpu.storage.sst import BlockCache
    bc = BlockCache(capacity_bytes=1000)

    class Slab:
        pass

    bc.put("a", Slab(), 400)
    bc.put("b", Slab(), 400)
    m = _mgr([], block_cache=bc)
    assert m.block_cache_tracker.consumption() == 800
    # driving the tracker over its limit evicts LRU entries
    m.block_cache_tracker._gc(500)
    assert bc.used <= 400


def test_memtable_and_db_report_oldest_write(tmp_path):
    from yugabyte_tpu.storage.db import DB
    from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
    db = DB(str(tmp_path / "db"))
    assert db.memstore_bytes() == 0
    assert db.oldest_memstore_write_s() is None
    db.write_batch([(b"k1", DocHybridTime(HybridTime(100), 0), b"v1")])
    t0 = db.oldest_memstore_write_s()
    assert db.memstore_bytes() > 0 and t0 is not None
    db.write_batch([(b"k2", DocHybridTime(HybridTime(101), 0), b"v2")])
    assert db.oldest_memstore_write_s() == t0   # first write wins
    db.flush()
    assert db.memstore_bytes() == 0
    assert db.oldest_memstore_write_s() is None
    db.close()


def test_tablet_server_owns_memory_manager(tmp_path):
    """The live TabletServer wires the arbiter + /memz tracker tree."""
    from yugabyte_tpu.tserver.tablet_server import (
        TabletServer, TabletServerOptions)
    ts = TabletServer(TabletServerOptions(
        server_id="ts-mm", fs_root=str(tmp_path / "fs"), port=0,
        master_addrs=[],
        tablet_options_factory=lambda: None))
    try:
        assert ts.memory_manager is not None
        assert ts.memory_manager.memstore_tracker.limit > 0
        assert ts.memory_manager.flush_tablet_if_limit_exceeded() == 0
    finally:
        ts.shutdown()
