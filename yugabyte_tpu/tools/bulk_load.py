"""yb-bulk-load: CSV -> table loader through the client write path.

Capability parity with the reference's bulk loader (ref:
src/yb/tools/yb_bulk_load.cc / bulk_load_tool.cc — partition input rows,
batch them per tablet, drive them in at full write-path speed). Rows ride
the ordinary client session (meta-cache routing + per-tablet batching,
client/session.py), so everything downstream — replication, indexes,
backpressure — behaves exactly as production writes do.

CSV shape: a header row naming columns; every key column of the table must
be present. Values parse by the column's schema type.

Usage: python -m yugabyte_tpu.tools.bulk_load --master <host:port> \
           --namespace db --table t --csv data.csv [--batch 512]
"""

from __future__ import annotations

import argparse
import csv
import json
import sys
import time

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.session import YBSession
from yugabyte_tpu.common.schema import DataType
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import StatusError


def _parse(raw: str, dtype: DataType):
    if raw == "":
        return None
    if dtype in (DataType.INT32, DataType.INT64, DataType.TIMESTAMP):
        return int(raw)
    if dtype in (DataType.FLOAT, DataType.DOUBLE):
        return float(raw)
    if dtype == DataType.BOOL:
        return raw.strip().lower() in ("1", "true", "t", "yes")
    if dtype == DataType.BINARY:
        return bytes.fromhex(raw)
    return raw  # STRING


def load_csv(client: YBClient, namespace: str, table_name: str,
             csv_path: str, batch: int = 512) -> dict:
    """Load every CSV row as an INSERT; returns {rows, seconds, rows_per_sec}."""
    table = client.open_table(namespace, table_name)
    schema = table.schema
    key_cols = [c.name for c in
                schema.hash_columns + schema.range_columns]
    value_cols = {c.name: c.type for c in schema.value_columns
                  if not c.dropped}
    types = {c.name: c.type for c in schema.columns}
    session = YBSession(client)
    n = 0
    t0 = time.time()
    with open(csv_path, newline="") as f:
        reader = csv.DictReader(f)
        missing = [k for k in key_cols if k not in (reader.fieldnames or ())]
        if missing:
            raise ValueError(f"CSV lacks key columns: {missing}")
        for row in reader:
            n_hash = schema.num_hash_key_columns
            hashed = tuple(_parse(row[k], types[k])
                           for k in key_cols[:n_hash])
            ranged = tuple(_parse(row[k], types[k])
                           for k in key_cols[n_hash:])
            dk = DocKey(hash_components=hashed, range_components=ranged)
            values = {c: _parse(row[c], t) for c, t in value_cols.items()
                      if c in row}
            session.apply(table, QLWriteOp(WriteOpKind.INSERT, dk,
                                           values=values))
            n += 1
            if n % batch == 0:
                session.flush()
    session.flush()
    dt = time.time() - t0
    return {"rows": n, "seconds": round(dt, 2),
            "rows_per_sec": round(n / dt, 1) if dt else 0.0}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="yb-bulk-load")
    ap.add_argument("--master", required=True, action="append",
                    help="master address (repeatable)")
    ap.add_argument("--namespace", required=True)
    ap.add_argument("--table", required=True)
    ap.add_argument("--csv", required=True)
    ap.add_argument("--batch", type=int, default=512)
    args = ap.parse_args(argv)
    client = YBClient(args.master)
    try:
        stats = load_csv(client, args.namespace, args.table, args.csv,
                         args.batch)
        print(json.dumps(stats))
        return 0
    except (StatusError, ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
