"""Overload-protection unit tests (PR 12): bounded RPC service queue
(overflow -> typed retryable Overloaded + measured retry_after_ms hint,
deadline-expired queued calls dropped unexecuted, shutdown fails queued
calls immediately), Backoff honoring server retry_after hints, the
per-client retry-budget token bucket, YBSession's buffered-bytes
admission cap, and the unified write-pressure state machine."""

import threading
import time

import pytest

from yugabyte_tpu.rpc.messenger import (Messenger, Overloaded,
                                        RemoteError, RpcTimeout,
                                        ServiceUnavailable,
                                        is_overloaded_error)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.backoff import (Backoff, RetryBudget,
                                        RetryBudgetExhausted)
from yugabyte_tpu.utils.status import Code, Status, StatusError


class _FlagScope:
    def __init__(self, **kv):
        self.kv = kv
        self.old = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.old[k] = flags.get_flag(k)
            flags.set_flag(k, v)
        return self

    def __exit__(self, *a):
        for k, v in self.old.items():
            flags.set_flag(k, v)


class _GatedService:
    """Handlers park on an event so tests can clog the service pool
    deterministically and observe what queued calls do."""

    def __init__(self):
        self.gate = threading.Event()
        self.ran = []          # mth args that actually EXECUTED
        self.lock = threading.Lock()

    def blocked(self, tag):
        with self.lock:
            self.ran.append(tag)
        self.gate.wait(timeout=30)
        return tag

    def quick(self, tag):
        with self.lock:
            self.ran.append(tag)
        return tag

    def overloaded_once(self, state={"n": 0}):
        state["n"] += 1
        if state["n"] == 1:
            raise Overloaded("write-pressure hard limit; retry later",
                             retry_after_ms=123, throttle="memstore")
        return state["n"]


# --------------------------------------------------------------- RPC queue
def test_queue_overflow_returns_typed_overloaded_with_hint():
    with _FlagScope(rpc_service_pool_threads=1,
                    rpc_service_queue_depth=1):
        server = Messenger("ovf-server")
        svc = _GatedService()
        server.register_service("gated", svc)
        client = Messenger("ovf-client")
        try:
            errs = []

            def bg(tag):
                try:
                    client.call(server.address, "gated", "blocked",
                                timeout_s=30, tag=tag)
                except Exception as e:  # noqa: BLE001 — asserted below
                    errs.append(e)

            # call 1 occupies the single worker; call 2 fills the queue
            t1 = threading.Thread(target=bg, args=("a",), daemon=True)
            t1.start()
            deadline = time.monotonic() + 5
            while not svc.ran and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc.ran == ["a"]
            t2 = threading.Thread(target=bg, args=("b",), daemon=True)
            t2.start()
            deadline = time.monotonic() + 5
            while server._service_pool.queue_len() < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            # call 3 overflows: typed retryable Overloaded NOW, not a
            # 30s queue-wait
            t0 = time.monotonic()
            with pytest.raises(RemoteError) as ei:
                client.call(server.address, "gated", "blocked",
                            timeout_s=30, tag="c")
            assert time.monotonic() - t0 < 5
            e = ei.value
            assert e.status.code == Code.BUSY
            assert e.extra.get("overloaded") is True
            assert e.extra.get("retry_after_ms") >= 10
            assert is_overloaded_error(e)
            assert server._c_queue_overflow.value() == 1
            # the overflowed call never executed
            svc.gate.set()
            t1.join(timeout=10)
            t2.join(timeout=10)
            assert not errs
            assert sorted(svc.ran) == ["a", "b"]
        finally:
            svc.gate.set()
            client.shutdown()
            server.shutdown()


def test_deadline_expired_queued_calls_never_execute():
    with _FlagScope(rpc_service_pool_threads=1,
                    rpc_service_queue_depth=64):
        server = Messenger("exp-server")
        svc = _GatedService()
        server.register_service("gated", svc)
        client = Messenger("exp-client")
        try:
            t1 = threading.Thread(
                target=lambda: client.call(server.address, "gated",
                                           "blocked", timeout_s=30,
                                           tag="clog"),
                daemon=True)
            t1.start()
            deadline = time.monotonic() + 5
            while not svc.ran and time.monotonic() < deadline:
                time.sleep(0.01)
            # short-deadline call lands in the queue behind the clog and
            # times out CLIENT-side while still queued
            with pytest.raises(RpcTimeout):
                client.call(server.address, "gated", "quick",
                            timeout_s=0.3, tag="expired")
            time.sleep(0.1)   # let the expiry fully lapse server-side
            svc.gate.set()    # unclog: the worker now drains the queue
            t1.join(timeout=10)
            deadline = time.monotonic() + 5
            while server._c_expired_in_queue.value() < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            # counted, and provably never executed
            assert server._c_expired_in_queue.value() == 1
            assert "expired" not in svc.ran
            # queue-time histogram recorded next to the duration one
            qh = server._method_histogram("gated", "quick", kind="queue")
            assert qh.count() >= 1
        finally:
            svc.gate.set()
            client.shutdown()
            server.shutdown()


def test_shutdown_fails_queued_inbound_calls_immediately():
    """Satellite regression (inbound mirror of the PR-1 outbound close
    fix): Messenger.shutdown() must answer queued-but-not-executing
    inbound calls NOW instead of executing them against torn-down
    services or silently dropping them into a full client timeout."""
    with _FlagScope(rpc_service_pool_threads=1,
                    rpc_service_queue_depth=64):
        server = Messenger("shut-server")
        svc = _GatedService()
        server.register_service("gated", svc)
        client = Messenger("shut-client")
        out = {}
        try:
            def clog():
                try:
                    client.call(server.address, "gated", "blocked",
                                timeout_s=30, tag="clog")
                except (RemoteError, ServiceUnavailable, RpcTimeout):
                    pass   # in-flight call torn down by shutdown: fine

            t1 = threading.Thread(target=clog, daemon=True)
            t1.start()
            deadline = time.monotonic() + 5
            while not svc.ran and time.monotonic() < deadline:
                time.sleep(0.01)

            def bg_queued():
                t0 = time.monotonic()
                try:
                    client.call(server.address, "gated", "quick",
                                timeout_s=30, tag="queued")
                    out["result"] = "ok"
                except Exception as e:  # noqa: BLE001 — asserted below
                    out["err"] = e
                out["elapsed"] = time.monotonic() - t0

            t2 = threading.Thread(target=bg_queued, daemon=True)
            t2.start()
            deadline = time.monotonic() + 5
            while server._service_pool.queue_len() < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            server.shutdown()
            t2.join(timeout=10)
            assert not t2.is_alive(), "queued caller still waiting"
            # failed immediately (not its 30s timeout), never executed
            assert out.get("err") is not None, out
            assert out["elapsed"] < 10
            assert isinstance(out["err"],
                              (RemoteError, ServiceUnavailable))
            if isinstance(out["err"], RemoteError):
                assert out["err"].extra.get("shutting_down") is True
                assert out["err"].status.code == Code.SERVICE_UNAVAILABLE
            assert "queued" not in svc.ran
            assert server._c_shed_at_shutdown.value() == 1
        finally:
            svc.gate.set()
            client.shutdown()


def test_overloaded_error_crosses_wire_with_extras():
    server = Messenger("ow-server")
    server.register_service("gated", _GatedService())
    client = Messenger("ow-client")
    try:
        with pytest.raises(RemoteError) as ei:
            client.call(server.address, "gated", "overloaded_once")
        assert ei.value.status.code == Code.BUSY
        assert ei.value.extra["overloaded"] is True
        assert ei.value.extra["retry_after_ms"] == 123
        assert ei.value.extra["throttle"] == "memstore"
        # second call: pressure relieved
        assert client.call(server.address, "gated",
                           "overloaded_once") == 2
    finally:
        client.shutdown()
        server.shutdown()


# ------------------------------------------------------------ Backoff hints
def test_backoff_honors_retry_after_hint():
    b = Backoff(base_s=0.01, cap_s=0.05, rng=None)
    b.note_server_hint(700)
    d = b.next_delay()
    assert d >= 0.7            # hint floors the delay, even past cap_s
    assert b.next_delay() <= 0.05   # consumed: back to jittered/capped


def test_backoff_hint_clamped_to_deadline():
    b = Backoff(base_s=0.01, cap_s=0.05, deadline_s=0.2)
    b.note_server_hint(5000)
    assert b.next_delay() <= 0.2 + 1e-6


def test_backoff_hint_takes_max_of_hints():
    b = Backoff(base_s=0.01, cap_s=0.05)
    b.note_server_hint(100)
    b.note_server_hint(400)
    b.note_server_hint(200)
    assert 0.4 <= b.next_delay() < 0.5


# ------------------------------------------------------------- retry budget
def test_retry_budget_exhaustion_is_typed():
    rb = RetryBudget(capacity=2, refill_per_s=0.0)
    assert rb.try_spend() and rb.try_spend()
    with pytest.raises(RetryBudgetExhausted) as ei:
        rb.spend_or_raise("write tablet t1", last_err="NOT_LEADER")
    e = ei.value
    assert isinstance(e, StatusError)
    assert e.status.code == Code.BUSY
    assert e.extra["overloaded"] and e.extra["retry_budget_exhausted"]
    assert "NOT_LEADER" in str(e)
    assert rb.exhausted_total == 1 and rb.spent_total == 2


def test_retry_budget_refills_over_time():
    rb = RetryBudget(capacity=1, refill_per_s=50.0)
    assert rb.try_spend()
    assert not rb.try_spend()
    time.sleep(0.05)
    assert rb.try_spend()   # ~2.5 tokens refilled, capped at 1


def test_client_walk_draws_from_budget_and_honors_hint():
    """_tablet_call through a stub messenger: an overloaded rejection is
    retried AFTER at least the server's retry_after hint, and once the
    budget is dry the walk surfaces RetryBudgetExhausted instead of
    burning all retry rounds."""
    from yugabyte_tpu.client.client import YBClient

    class _StubTablet:
        tablet_id = "t1"

        class partition:
            start = b""

        @staticmethod
        def candidate_addrs():
            return ["127.0.0.1:1"]

        @staticmethod
        def mark_leader(addr):
            pass

    class _StubTable:
        table_id = "tbl"
        name = "tbl"

    class _StubMessenger:
        def __init__(self, fail_n, retry_after_ms):
            self.calls = []
            self.fail_n = fail_n
            self.retry_after_ms = retry_after_ms

        def call(self, addr, svc, mth, timeout_s=None, **args):
            self.calls.append(time.monotonic())
            if len(self.calls) <= self.fail_n:
                raise RemoteError(
                    Status(Code.BUSY, "queue full; retry later"),
                    extra={"overloaded": True,
                           "retry_after_ms": self.retry_after_ms})
            return {"ok": True}

        def shutdown(self):
            pass

    class _StubMeta:
        @staticmethod
        def lookup_tablet(table_id, pk, refresh=False):
            return _StubTablet()

    # hint honored: one rejection, then success after >= 400ms
    stub = _StubMessenger(fail_n=1, retry_after_ms=400)
    client = YBClient([], messenger=stub)
    client.meta_cache = _StubMeta()
    t0 = time.monotonic()
    ret = client._tablet_call(_StubTable(), _StubTablet(), "write",
                              refresh_key=b"")
    assert ret == {"ok": True} and len(stub.calls) == 2
    assert time.monotonic() - t0 >= 0.4

    # budget exhaustion surfaces typed, before the 12 retry rounds
    with _FlagScope(client_retry_budget_tokens=2,
                    client_retry_budget_refill_per_s=0.0):
        stub = _StubMessenger(fail_n=99, retry_after_ms=10)
        client = YBClient([], messenger=stub)
        client.meta_cache = _StubMeta()
        with pytest.raises(RetryBudgetExhausted):
            client._tablet_call(_StubTable(), _StubTablet(), "write",
                                refresh_key=b"")
        assert len(stub.calls) == 3   # first attempt free + 2 budgeted


# ------------------------------------------------------------- session cap
class _FakePartition:
    start = b""


class _FakeTablet:
    tablet_id = "ft1"
    partition = _FakePartition()


class _FakeMetaCache:
    def lookup_tablet(self, table_id, pk, refresh=False):
        return _FakeTablet()


class _FakeTable:
    table_id = "ftbl"
    name = "ftbl"

    @staticmethod
    def partition_key_for(dk):
        return b"pk"


class _FakeClient:
    def __init__(self):
        self.meta_cache = _FakeMetaCache()
        self.written = []
        self.gate = threading.Event()
        self.gate.set()
        self._lock = threading.Lock()

    def write(self, table, ops, tablet=None):
        self.gate.wait(timeout=30)
        with self._lock:
            self.written.extend(ops)


def _mk_op(i, nbytes=100):
    from yugabyte_tpu.docdb.doc_key import DocKey
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    return QLWriteOp(WriteOpKind.INSERT,
                     DocKey(range_components=(f"k{i:04d}",)),
                     {"v": "x" * nbytes})


def test_session_buffer_cap_raises_typed_when_nonblocking():
    from yugabyte_tpu.client.session import SessionBufferFull, YBSession
    from yugabyte_tpu.client.session import _op_bytes
    sz = _op_bytes(_mk_op(0))
    with _FlagScope(ybsession_max_buffered_bytes=2 * sz + 10):
        fc = _FakeClient()
        fc.gate.clear()   # sends (if any) would hang: cap is the gate
        s = YBSession(fc)
        s.apply(_FakeTable(), _mk_op(1))
        s.apply(_FakeTable(), _mk_op(2))
        with pytest.raises(SessionBufferFull) as ei:
            s.apply(_FakeTable(), _mk_op(3), block=False)
        assert ei.value.extra["overloaded"]
        assert ei.value.extra["session_buffer_full"]
        assert ei.value.status.code == Code.BUSY
        fc.gate.set()
        s.flush()
        assert len(fc.written) == 2


def test_session_buffer_cap_blocks_then_drains():
    from yugabyte_tpu.client.session import YBSession, _op_bytes
    sz = _op_bytes(_mk_op(0))
    with _FlagScope(ybsession_max_buffered_bytes=2 * sz + 10):
        fc = _FakeClient()
        fc.gate.clear()
        s = YBSession(fc)
        s.apply(_FakeTable(), _mk_op(1))
        s.apply(_FakeTable(), _mk_op(2))
        done = threading.Event()

        def blocked_apply():
            # over the cap: blocks, self-flushes the buffer in the
            # background, and completes once a send drains
            s.apply(_FakeTable(), _mk_op(3))
            done.set()

        t = threading.Thread(target=blocked_apply, daemon=True)
        t.start()
        time.sleep(0.3)
        assert not done.is_set(), "apply() did not block at the cap"
        fc.gate.set()   # sends drain -> cap frees -> apply completes
        assert done.wait(timeout=10), "apply() never unblocked"
        assert s.buffer_full_waits_total >= 1
        s.flush()
        assert len(fc.written) == 3
        assert s.outstanding_bytes() == 0


def test_session_admits_oversized_op_into_empty_buffer():
    from yugabyte_tpu.client.session import YBSession
    with _FlagScope(ybsession_max_buffered_bytes=64):
        fc = _FakeClient()
        s = YBSession(fc)
        s.apply(_FakeTable(), _mk_op(1, nbytes=4096))  # must not wedge
        s.flush()
        assert len(fc.written) == 1


# -------------------------------------------------------- write admission
def _mk_tablet(tmp_path, tid="adm"):
    from yugabyte_tpu.common.schema import (ColumnSchema, DataType,
                                            Schema)
    from yugabyte_tpu.tablet.tablet import Tablet, TabletOptions
    schema = Schema([ColumnSchema("k", DataType.STRING),
                     ColumnSchema("v", DataType.INT64)],
                    num_hash_key_columns=0, num_range_key_columns=1)
    return Tablet(tid, str(tmp_path / tid), schema,
                  options=TabletOptions(auto_compact=False)), schema


def _mk_write(k):
    from yugabyte_tpu.docdb.doc_key import DocKey
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    return QLWriteOp(WriteOpKind.INSERT, DocKey(range_components=(k,)),
                     {"v": 1})


def test_admission_memstore_hard_rejects_with_throttle_extras(tmp_path):
    from yugabyte_tpu.utils.mem_tracker import MemTracker
    t, _ = _mk_tablet(tmp_path)
    try:
        used = {"n": 0}
        tracker = MemTracker(1000, "memstore-test",
                             consumption_fn=lambda: used["n"])
        t.admission.bind_memstore(tracker)
        t.write([_mk_write("ok")])          # healthy: admits
        used["n"] = 2000                    # way past the reject line
        with pytest.raises(Overloaded) as ei:
            t.write([_mk_write("shed")])
        e = ei.value
        assert e.status.code == Code.BUSY
        assert e.extra["overloaded"] and e.extra["throttle"] == "memstore"
        assert e.extra["retry_after_ms"] >= 50
        assert "retry later" in str(e)
        assert t.metric_write_rejections.value() == 1
        snap = t.admission.snapshot()
        assert snap["state"] == "hard" and snap["signal"] == "memstore"
        assert snap["rejections_by_signal"] == {"memstore": 1}
        used["n"] = 0                       # flush caught up: admits again
        t.write([_mk_write("again")])
        assert t.admission.snapshot()["state"] == "healthy"
    finally:
        t.close()


def test_admission_memstore_soft_delays(tmp_path):
    from yugabyte_tpu.utils.mem_tracker import MemTracker
    t, _ = _mk_tablet(tmp_path, "adm2")
    try:
        used = {"n": 0}
        t.admission.bind_memstore(MemTracker(
            1000, "memstore-test2", consumption_fn=lambda: used["n"]))
        with _FlagScope(write_backpressure_max_delay_ms=150):
            used["n"] = 900   # between soft (85%) and reject (95%)
            t0 = time.monotonic()
            t.write([_mk_write("slow")])
            assert time.monotonic() - t0 >= 0.04
            assert t.admission.snapshot()["state"] == "soft"
            assert t.admission.delays_total >= 1
    finally:
        t.close()


def test_admission_wal_backlog_rejects(tmp_path):
    t, _ = _mk_tablet(tmp_path, "adm3")
    try:
        backlog = {"n": 0}
        t.admission.bind_wal(lambda: backlog["n"])
        with _FlagScope(wal_backlog_soft_entries=10,
                        wal_backlog_hard_entries=20):
            t.write([_mk_write("a")])
            backlog["n"] = 25
            with pytest.raises(Overloaded) as ei:
                t.write([_mk_write("b")])
            assert ei.value.extra["throttle"] == "wal"
            backlog["n"] = 0
            t.write([_mk_write("c")])
    finally:
        t.close()


def test_admission_sst_signal_keeps_legacy_behavior(tmp_path):
    """The SST arm must keep the pre-unification contract: retryable
    'retry later' rejection at the hard limit + the tablet counter
    (test_backpressure asserts the same from the outside)."""
    t, _ = _mk_tablet(tmp_path, "adm4")
    try:
        with _FlagScope(sst_files_soft_limit=1, sst_files_hard_limit=2):
            t.write([_mk_write("a")])
            t.regular_db.flush()
            t.write([_mk_write("b")])
            t.regular_db.flush()
            assert t.regular_db.n_live_files >= 2
            with pytest.raises(StatusError) as ei:
                t.write([_mk_write("c")])
            assert "retry later" in str(ei.value)
            assert ei.value.extra["throttle"] == "sst"
            assert t.metric_write_rejections.value() >= 1
    finally:
        t.close()


def test_wal_backlog_counts_queued_entries(tmp_path):
    from yugabyte_tpu.consensus.log import Log, LogEntry
    log = Log(str(tmp_path / "wal"))
    try:
        assert log.backlog() == 0
        done = threading.Event()
        log.append_async([LogEntry(1, i + 1, b"x") for i in range(3)],
                         callback=lambda err: done.set())
        # the appender may already have drained it; only assert the
        # probe returns and lands at zero once the queue settles
        assert done.wait(timeout=10)
        deadline = time.monotonic() + 5
        while log.backlog() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert log.backlog() == 0
    finally:
        log.close()
