"""ysck: cluster consistency checker (ref: src/yb/tools/ysck.cc +
cluster_verifier.h).

    python -m yugabyte_tpu.tools.ysck --masters host:port[,host:port]

Walks every table: checks tserver liveness, per-tablet leadership,
cross-replica checksums at one read time per tablet (the same
visibility-resolved digest the crash-fault harness asserts on), and each
replica's integrity state (at-rest scrub timestamp/totals, corruption
flags, digest-mismatch counts — the scrub_status RPC). Exit 0 = healthy,
1 = problems found (divergence, detected corruption, repairs in flight).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def check_cluster(master_addrs: List[str], out=None) -> int:
    from yugabyte_tpu.client.client import YBClient
    from yugabyte_tpu.utils.status import StatusError
    out = out or sys.stdout
    problems = 0
    client = YBClient(master_addrs)
    try:
        tservers = client.list_tservers()
        dead = [t for t in tservers if not t.get("alive")]
        print(f"tservers: {len(tservers)} ({len(dead)} dead)", file=out)
        for t in dead:
            problems += 1
            print(f"  DEAD: {t['server_id']} @ {t['addr']}", file=out)
        for table in client.list_tables():
            tid = table["table_id"]
            name = f"{table['namespace']}.{table['name']}"
            locs = client._master_call("get_table_locations", table_id=tid)
            bad = 0
            total_rows = 0
            for loc in locs:
                if loc.get("leader") is None:
                    problems += 1
                    bad += 1
                    print(f"  {name}/{loc['tablet_id']}: NO LEADER",
                          file=out)
                    continue
                addrs = [r["addr"] for r in loc["replicas"] if r["addr"]]
                read_ht = None
                sums = {}
                for addr in addrs:
                    try:
                        if read_ht is None:
                            read_ht = client._messenger.call(
                                addr, "tserver", "scan",
                                tablet_id=loc["tablet_id"],
                                limit=1)["read_ht"]
                        resp = client._messenger.call(
                            addr, "tserver", "checksum_tablet",
                            timeout_s=30.0, tablet_id=loc["tablet_id"],
                            read_ht=read_ht)
                        sums[addr] = (resp["checksum"], resp["entries"])
                    except StatusError:
                        continue  # not the leader for the pin; follower ok
                if len({c for c, _n in sums.values()}) > 1:
                    problems += 1
                    bad += 1
                    print(f"  {name}/{loc['tablet_id']}: REPLICA "
                          f"DIVERGENCE {sums}", file=out)
                elif sums:
                    total_rows += next(iter(sums.values()))[1]
                # per-replica integrity state: scrub recency + detected
                # corruption (a corrupt replica is being rebuilt — count
                # it as a problem so operators see the repair in flight)
                for addr in addrs:
                    try:
                        st = client._messenger.call(
                            addr, "tserver", "scrub_status",
                            timeout_s=10.0, tablet_id=loc["tablet_id"])
                    except StatusError:
                        continue  # replica mid-rebuild / older server
                    scrub = st.get("scrub") or {}
                    corrupt = scrub.get("corrupt", 0)
                    mism = scrub.get("replica_mismatches", 0)
                    last = scrub.get("last_scrub_ts")
                    if st.get("failed_corrupt") or corrupt:
                        problems += 1
                        bad += 1
                        print(f"  {name}/{loc['tablet_id']}@{addr}: "
                              f"CORRUPT replica (scrub errors={corrupt},"
                              f" rebuilding)", file=out)
                    elif last or mism:
                        import time as _time
                        age = (f"{_time.time() - last:.0f}s ago"
                               if last else "never")
                        print(f"  {name}/{loc['tablet_id']}@{addr}: "
                              f"scrub {age}, digest mismatches={mism}",
                              file=out)
            status = "OK" if bad == 0 else f"{bad} bad tablets"
            print(f"table {name}: {len(locs)} tablets, ~{total_rows} "
                  f"rows: {status}", file=out)
        print("ysck: " + ("OK" if problems == 0
                          else f"{problems} problem(s)"), file=out)
        return 0 if problems == 0 else 1
    finally:
        client.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="ysck")
    ap.add_argument("--masters", required=True,
                    help="comma-separated master addresses")
    args = ap.parse_args(argv)
    return check_cluster(args.masters.split(","))


if __name__ == "__main__":
    sys.exit(main())
