"""ctypes bindings for the native compaction shell (native/compaction_engine.cc).

The byte path of the compaction job (block decode, merge+GC, survivor
gather, block encode+write — ref: rocksdb/db/compaction_job.cc:442 and hot
loop #3 at :958-1024) runs in C++; Python keeps metadata authority: index
block, bloom filter and props assembly, frontier merge, VersionSet wiring.

Two modes share the engine:
  - full native: ce_job_merge runs the shared heap-merge + GC filter
    (native/merge_gc_core.h),
  - device decisions: the TPU kernel's (perm, keep, mk) are injected via
    ce_job_set_survivors and the engine only materializes output bytes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.utils import flags

flags.define_flag("compaction_native_threads",
                  min(4, os.cpu_count() or 1),
                  "worker threads for native block decode/encode "
                  "(the reference runs multiple subcompaction threads, "
                  "compaction_job.cc:456-468); capped at the core count — "
                  "oversubscribing memory-bound encode threads on a "
                  "1-core box only adds contention")

_lib = None
_lib_lock = threading.Lock()

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from yugabyte_tpu.utils.native_build import build_native_lib
        lib_path = build_native_lib("compaction_engine.cc",
                                    "libcompaction_engine.so",
                                    extra_args=("-lz", "-lpthread"))
        lib = ctypes.CDLL(lib_path)
        lib.ce_job_new.restype = ctypes.c_void_p
        lib.ce_job_new.argtypes = [ctypes.c_int32]
        lib.ce_job_free.argtypes = [ctypes.c_void_p]
        lib.ce_job_error.restype = ctypes.c_char_p
        lib.ce_job_error.argtypes = [ctypes.c_void_p]
        lib.ce_job_add_input.argtypes = [
            ctypes.c_void_p, _u8p, ctypes.c_int64, _i64p, _i32p, _i32p,
            ctypes.c_int32]
        lib.ce_job_prepare.restype = ctypes.c_int64
        lib.ce_job_prepare.argtypes = [ctypes.c_void_p]
        lib.ce_job_add_raw.argtypes = [
            ctypes.c_void_p, _u8p, _i64p, ctypes.c_int64, _u64p,
            ctypes.POINTER(ctypes.c_uint32), _u8p, _i64p]
        lib.ce_job_sort_all.restype = ctypes.c_int64
        lib.ce_job_sort_all.argtypes = [ctypes.c_void_p]
        lib.ce_job_props.argtypes = [ctypes.c_void_p, _u64p,
                                     _i32p]
        lib.ce_job_merge.restype = ctypes.c_int64
        lib.ce_job_merge.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32]
        lib.ce_job_set_survivors.argtypes = [
            ctypes.c_void_p, _i64p, _u8p, ctypes.c_int64]
        lib.ce_job_append_survivors.argtypes = [
            ctypes.c_void_p, _i64p, _u8p, ctypes.c_int64]
        lib.ce_job_rows.restype = ctypes.c_int64
        lib.ce_job_rows.argtypes = [ctypes.c_void_p]
        lib.ce_job_n_survivors.restype = ctypes.c_int64
        lib.ce_job_n_survivors.argtypes = [ctypes.c_void_p]
        lib.ce_job_write_output.restype = ctypes.c_int64
        lib.ce_job_write_output.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_int32, _u8p, ctypes.c_int32]
        lib.ce_out_n_blocks.restype = ctypes.c_int32
        lib.ce_out_n_blocks.argtypes = [ctypes.c_void_p]
        lib.ce_out_block_meta.argtypes = [ctypes.c_void_p, _i64p, _i32p,
                                          _i32p, _i32p]
        lib.ce_out_last_keys.argtypes = [ctypes.c_void_p, _u8p]
        lib.ce_out_bloom_hashes.argtypes = [ctypes.c_void_p, _u64p]
        lib.ce_out_first_key.restype = ctypes.c_int32
        lib.ce_out_first_key.argtypes = [ctypes.c_void_p, _u8p,
                                         ctypes.c_int32]
        lib.ce_out_last_key.restype = ctypes.c_int32
        lib.ce_out_last_key.argtypes = [ctypes.c_void_p, _u8p,
                                        ctypes.c_int32]
        lib.ce_bloom_build.argtypes = [
            _u64p, ctypes.c_int64, _u8p, ctypes.c_uint64, ctypes.c_int32]
        lib.ce_runcache_export.restype = ctypes.c_int64
        lib.ce_runcache_export.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _u8p,
            ctypes.c_int32]
        lib.ce_runcache_entry_bytes.restype = ctypes.c_int64
        lib.ce_runcache_entry_bytes.argtypes = [ctypes.c_int64]
        lib.ce_runcache_drop.argtypes = [ctypes.c_int64]
        lib.ce_runcache_bytes.restype = ctypes.c_int64
        lib.ce_job_add_cached.restype = ctypes.c_int32
        lib.ce_job_add_cached.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ce_job_prepare_cached.restype = ctypes.c_int64
        lib.ce_job_prepare_cached.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


_available: Optional[bool] = None


def available() -> bool:
    """Build-once probe; a failed compile is cached so the hot path does
    not re-spawn a doomed g++ per compaction pick."""
    global _available
    if _available is None:
        try:
            _load()
            _available = True
        except Exception:  # yblint: contained(build probe — cached False routes every job to the Python shell)
            _available = False
    return _available


def bloom_build(hashes: np.ndarray, bits: np.ndarray,
                m_bits: int, k: int) -> None:
    """Scatter bloom bits natively (storage/bloom.py hot path)."""
    lib = _load()
    h = np.ascontiguousarray(hashes, dtype=np.uint64)
    lib.ce_bloom_build(h.ctypes.data_as(_u64p),
                       ctypes.c_int64(len(h)),
                       bits.ctypes.data_as(_u8p),
                       ctypes.c_uint64(m_bits), ctypes.c_int32(k))


def runcache_drop(run_id: int) -> None:
    """Drop one entry from the native run cache (jobs holding it keep a
    reference until they free)."""
    _load().ce_runcache_drop(ctypes.c_int64(run_id))


def runcache_bytes() -> int:
    """Total host RAM held by the native run cache."""
    return int(_load().ce_runcache_bytes())


def runcache_entry_bytes(run_id: int) -> int:
    return int(_load().ce_runcache_entry_bytes(ctypes.c_int64(run_id)))


class NativeCompactionJob:
    """One compaction: add inputs -> prepare -> merge (or inject) -> write.

    Inputs are SSTReader-level artifacts: the raw data-file bytes plus the
    parsed block handles (Python already holds both — the base-file index
    stays Python-authority).
    """

    def __init__(self, n_threads: Optional[int] = None):
        self._lib = _load()
        nt = n_threads if n_threads is not None else \
            flags.get_flag("compaction_native_threads")
        self._job = self._lib.ce_job_new(ctypes.c_int32(nt))
        self._keepalive: List[object] = []   # input byte buffers
        self.rows_in = 0
        self.n_survivors = 0

    def close(self):
        if self._job:
            self._lib.ce_job_free(self._job)
            self._job = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _err(self) -> str:
        return self._lib.ce_job_error(self._job).decode()

    def add_input(self, data: bytes,
                  handles: Sequence[Tuple[int, int, int]]) -> None:
        self._keepalive.append(data)
        nb = len(handles)
        offs = np.asarray([h[0] for h in handles], dtype=np.int64)
        sizes = np.asarray([h[1] for h in handles], dtype=np.int32)
        counts = np.asarray([h[2] for h in handles], dtype=np.int32)
        self._keepalive += [offs, sizes, counts]
        # zero-copy: point straight at the bytes object's buffer (kept alive
        # in _keepalive until ce_job_free)
        ptr = ctypes.cast(ctypes.c_char_p(data), _u8p)
        self._lib.ce_job_add_input(
            self._job, ptr, ctypes.c_int64(len(data)),
            offs.ctypes.data_as(_i64p), sizes.ctypes.data_as(_i32p),
            counts.ctypes.data_as(_i32p), ctypes.c_int32(nb))

    def prepare(self) -> int:
        n = int(self._lib.ce_job_prepare(self._job))
        if n < 0:
            # prepare fails only in block decode (magic/CRC/size checks,
            # native/compaction_engine.cc): the input bytes are corrupt.
            # Typed as Corruption so the DB parks STICKY and the replica
            # is rebuilt instead of retrying into the same bad bytes.
            from yugabyte_tpu.utils.status import Status, StatusError
            raise StatusError(Status.Corruption(
                f"native compaction prepare: {self._err()}"))
        self.rows_in = n
        return n

    def add_raw(self, keys_blob: bytes, key_offs: np.ndarray,
                ht: np.ndarray, wid: np.ndarray, vals_blob: bytes,
                val_offs: np.ndarray) -> int:
        """Ingest one packed run (the flush/bulk-load path): flags, TTL and
        doc_key_len are derived natively from the value control fields and
        key structure (ref: db/flush_job.cc WriteLevel0Table)."""
        n = len(key_offs) - 1
        key_offs = np.ascontiguousarray(key_offs, dtype=np.int64)
        ht = np.ascontiguousarray(ht, dtype=np.uint64)
        wid = np.ascontiguousarray(wid, dtype=np.uint32)
        val_offs = np.ascontiguousarray(val_offs, dtype=np.int64)
        self._keepalive += [keys_blob, key_offs, ht, wid, vals_blob, val_offs]
        self._lib.ce_job_add_raw(
            self._job, ctypes.cast(ctypes.c_char_p(keys_blob), _u8p),
            key_offs.ctypes.data_as(_i64p), ctypes.c_int64(n),
            ht.ctypes.data_as(_u64p),
            wid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.cast(ctypes.c_char_p(vals_blob), _u8p),
            val_offs.ctypes.data_as(_i64p))
        self.rows_in = n
        return n

    def sort_all(self) -> int:
        """Order the raw run by internal key (no-op scan when pre-sorted)
        and mark every row a survivor — flush keeps all versions."""
        self.n_survivors = int(self._lib.ce_job_sort_all(self._job))
        return self.n_survivors

    def props(self):
        """(max_expire_us, has_deep) for the base-file props."""
        mx = ctypes.c_uint64()
        deep = ctypes.c_int32()
        self._lib.ce_job_props(self._job, ctypes.byref(mx),
                               ctypes.byref(deep))
        return int(mx.value), bool(deep.value)

    def merge(self, cutoff_ht: int, is_major: bool,
              retain_deletes: bool = False) -> int:
        self.n_survivors = int(self._lib.ce_job_merge(
            self._job, ctypes.c_uint64(cutoff_ht),
            ctypes.c_int32(int(is_major)),
            ctypes.c_int32(int(retain_deletes))))
        return self.n_survivors

    def set_survivors(self, surv: np.ndarray, make_tomb: np.ndarray) -> None:
        surv = np.ascontiguousarray(surv, dtype=np.int64)
        mk = np.ascontiguousarray(make_tomb, dtype=np.uint8)
        self._lib.ce_job_set_survivors(
            self._job, surv.ctypes.data_as(_i64p), mk.ctypes.data_as(_u8p),
            ctypes.c_int64(len(surv)))
        self.n_survivors = len(surv)

    def append_survivors(self, surv: np.ndarray,
                         make_tomb: np.ndarray) -> None:
        """Stage-C streaming injection: append one pipeline chunk's
        survivors (already in global merged order — chunks are route-
        partitioned) so output spans covered by appended survivors can be
        written while later chunks still compute or transfer."""
        surv = np.ascontiguousarray(surv, dtype=np.int64)
        mk = np.ascontiguousarray(make_tomb, dtype=np.uint8)
        self._lib.ce_job_append_survivors(
            self._job, surv.ctypes.data_as(_i64p), mk.ctypes.data_as(_u8p),
            ctypes.c_int64(len(surv)))
        self.n_survivors += len(surv)

    def export_run(self, start: int, end: int,
                   tombstone_value: bytes) -> int:
        """Export survivors [start, end) into the native run cache —
        byte-equivalent to re-decoding the output file written for that
        range. Returns the run id (see storage/run_cache.py)."""
        tomb = np.ascontiguousarray(
            np.frombuffer(tombstone_value, dtype=np.uint8))
        rid = int(self._lib.ce_runcache_export(
            self._job, ctypes.c_int64(start), ctypes.c_int64(end),
            tomb.ctypes.data_as(_u8p), ctypes.c_int32(len(tombstone_value))))
        if rid < 0:
            raise RuntimeError(f"run cache export: {self._err()}")
        return rid

    def add_cached(self, run_id: int) -> None:
        """Append a run-cache entry as a job input (zero-decode path)."""
        if int(self._lib.ce_job_add_cached(
                self._job, ctypes.c_int64(run_id))) != 0:
            raise KeyError(f"run cache id {run_id} not present")

    def prepare_cached(self) -> int:
        """prepare() for all-cached inputs: no file read, no block decode."""
        n = int(self._lib.ce_job_prepare_cached(self._job))
        if n < 0:
            raise RuntimeError(f"native prepare_cached: {self._err()}")
        self.rows_in = n
        return n

    def write_output(self, start: int, end: int, data_path: str,
                     block_entries: int, compress: bool,
                     tombstone_value: bytes):
        """Write one output data file; returns (data_size, index_entries,
        bloom_hashes, first_key, last_key) for Python-side base assembly."""
        tomb = np.frombuffer(tombstone_value, dtype=np.uint8)
        size = int(self._lib.ce_job_write_output(
            self._job, ctypes.c_int64(start), ctypes.c_int64(end),
            data_path.encode(), ctypes.c_int32(block_entries),
            ctypes.c_int32(int(compress)),
            np.ascontiguousarray(tomb).ctypes.data_as(_u8p),
            ctypes.c_int32(len(tombstone_value))))
        if size < 0:
            raise RuntimeError(f"native compaction write: {self._err()}")
        nb = int(self._lib.ce_out_n_blocks(self._job))
        offs = np.zeros(nb, dtype=np.int64)
        sizes = np.zeros(nb, dtype=np.int32)
        counts = np.zeros(nb, dtype=np.int32)
        lk_lens = np.zeros(nb, dtype=np.int32)
        if nb:
            self._lib.ce_out_block_meta(
                self._job, offs.ctypes.data_as(_i64p),
                sizes.ctypes.data_as(_i32p), counts.ctypes.data_as(_i32p),
                lk_lens.ctypes.data_as(_i32p))
        lk_buf = np.zeros(max(1, int(lk_lens.sum())), dtype=np.uint8)
        if nb:
            self._lib.ce_out_last_keys(self._job,
                                       lk_buf.ctypes.data_as(_u8p))
        last_keys: List[bytes] = []
        p = 0
        for ln in lk_lens:
            last_keys.append(lk_buf[p: p + int(ln)].tobytes())
            p += int(ln)
        n_rows = end - start
        hashes = np.zeros(max(1, n_rows), dtype=np.uint64)
        if n_rows:
            self._lib.ce_out_bloom_hashes(self._job,
                                          hashes.ctypes.data_as(_u64p))
        def _fetch_key(fn):
            cap = 4096
            while True:
                kb = np.zeros(cap, dtype=np.uint8)
                ln = int(fn(self._job, kb.ctypes.data_as(_u8p),
                            ctypes.c_int32(cap)))
                if ln <= cap:
                    return kb[:ln].tobytes()
                cap = ln  # key longer than the guess: retry exact-sized

        first_key = _fetch_key(self._lib.ce_out_first_key)
        last_key = _fetch_key(self._lib.ce_out_last_key)
        index = list(zip(last_keys, offs.tolist(), sizes.tolist(),
                         counts.tolist()))
        return size, index, hashes[:n_rows], first_key, last_key
