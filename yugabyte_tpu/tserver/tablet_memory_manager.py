"""TabletMemoryManager: server-wide memstore arbitration + cache GC.

Capability parity with the reference (ref:
src/yb/tserver/tablet_memory_manager.h:39 — block-cache tracking with a
GarbageCollector, log-cache GC, and a background task that flushes the
tablet holding the OLDEST mutable memtable once the *global* memstore
limit is exceeded, tablet_memory_manager.cc:214-283 TabletToFlush /
FlushTabletIfLimitExceeded).

Design here: each tablet already flushes itself when its own memtable
crosses memstore_size_bytes (storage/db.py write_batch); this manager adds
the cross-tablet dimension — many tablets each slightly under their local
limit can still exhaust the server, so a background arbiter sums memstore
bytes across all hosted tablets and force-flushes oldest-first until under
the global limit. Trackers hang off the process root
(utils/mem_tracker.py) so /memz shows one coherent tree.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.mem_tracker import MemTracker, root_tracker
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("global_memstore_limit_bytes", 0,
                  "server-wide bound on summed memstore bytes; 0 = "
                  "global_memstore_fraction of the root tracker limit, "
                  "capped at 2 GiB (ref global_memstore_size_percentage / "
                  "global_memstore_size_mb_max)")
flags.define_flag("global_memstore_fraction", 0.10,
                  "fraction of the root memory limit given to the global "
                  "memstore when global_memstore_limit_bytes is 0")
flags.define_flag("memstore_arbitration_interval_s", 1.0,
                  "period of the background global-memstore check")


def _global_memstore_limit(root_limit: int) -> int:
    explicit = flags.get_flag("global_memstore_limit_bytes")
    if explicit:
        return explicit
    derived = int(root_limit * flags.get_flag("global_memstore_fraction"))
    # an unlimited root (limit<=0) must not derive a ZERO budget — 0 would
    # read as "flush everything always"; fall back to the 2 GiB cap
    return min(derived, 2 << 30) if derived > 0 else 2 << 30


class TabletMemoryManager:  # yblint: disable=ybsan-coverage (trackers/config are frozen before the arbiter thread starts — HB via Thread.start — and mutable accounting lives in MemTracker, which locks internally)
    """One per TabletServer. peers_fn returns the live TabletPeer list."""

    def __init__(self, peers_fn: Callable[[], List],
                 block_cache=None, log_cache_bytes_fn=None,
                 log_cache_evict=None, server_tracker: Optional[MemTracker] = None,
                 metric_entity=None, server_id: str = ""):
        self._peers_fn = peers_fn
        root = server_tracker or root_tracker()
        # id scoped by server: MiniCluster runs several tservers in one
        # process and each needs its own subtree under the process root
        self.server_tracker = root.find_or_create_child(
            f"tserver_{server_id}" if server_id else "tserver")
        self.memstore_tracker = MemTracker(
            _global_memstore_limit(root.limit), "memstore",
            parent=self.server_tracker,
            consumption_fn=self._total_memstore_bytes)
        self._root = root
        self.block_cache_tracker = None
        self._root_gc = None
        if block_cache is not None:
            self.block_cache_tracker = MemTracker(
                block_cache.capacity, "block_cache",
                parent=self.server_tracker,
                consumption_fn=lambda: block_cache.used)
            self.block_cache_tracker.add_gc_function(block_cache.evict)
            # process-level pressure sheds cache too (ref: InitBlockCache
            # registers the GC on the server tracker so root-limit checks
            # reach it); the arbiter loop drives root.limit_exceeded()
            self._root_gc = block_cache.evict
            root.add_gc_function(self._root_gc)
        self.log_cache_tracker = None
        if log_cache_bytes_fn is not None:
            self.log_cache_tracker = MemTracker(
                0, "log_cache", parent=self.server_tracker,
                consumption_fn=log_cache_bytes_fn)
            if log_cache_evict is not None:
                self.log_cache_tracker.add_gc_function(log_cache_evict)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._c_forced = None
        if metric_entity is not None:
            self._c_forced = metric_entity.counter(
                "global_memstore_forced_flushes_total",
                "tablet flushes forced by the global memstore limit")
        # observability hook mirroring TEST_listeners (ref header :65)
        self.flush_listeners: List[Callable[[str], None]] = []

    # ------------------------------------------------------------- lifecycle
    def init(self) -> None:
        self.bind_admission()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memstore-arbiter")
        self._thread.start()

    def bind_admission(self) -> None:
        """Hand the server-wide memstore tracker to every hosted
        tablet's write-admission state machine (tablet/admission.py) so
        write entry points shed on memstore pressure. Idempotent;
        re-applied every arbiter round so tablets created after init()
        get bound within one arbitration interval."""
        for peer in self._peers_fn():
            tablet = getattr(peer, "tablet", peer)
            admission = getattr(tablet, "admission", None)
            if admission is not None:
                admission.bind_memstore(self.memstore_tracker)

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # sever this server's subtree so an in-process restart with the
        # same server_id starts clean and /memz drops the dead trackers
        if self._root_gc is not None:
            self._root.remove_gc_function(self._root_gc)
        self.server_tracker.unregister_from_parent()

    def _loop(self) -> None:
        period = flags.get_flag("memstore_arbitration_interval_s")
        while not self._stop.wait(period):
            try:
                self.bind_admission()
                self.flush_tablet_if_limit_exceeded()
                # process-level pressure check: RSS over the root limit
                # sheds cache memory via the registered GC hooks
                self._root.limit_exceeded()
            except Exception as e:
                TRACE("memstore arbiter error: %s", e)

    # ------------------------------------------------------------ arbitration
    def _total_memstore_bytes(self) -> int:
        total = 0
        for peer in self._peers_fn():
            tablet = getattr(peer, "tablet", peer)
            try:
                total += tablet.memstore_bytes()
            except Exception:
                pass
        return total

    def flush_tablet_if_limit_exceeded(self) -> int:
        """Flush oldest-first until the global memstore is under its limit
        (ref tablet_memory_manager.cc:253 TabletToFlush picks the oldest
        mutable memtable write across peers). One scan per round: sizes and
        ages are snapshotted once, then tablets are flushed in age order
        with a running total — each tablet is attempted at most once, so a
        flush that no-ops (already in progress) cannot stall the round."""
        limit = self.memstore_tracker.limit
        if limit <= 0:      # unlimited (MemTracker convention)
            return 0
        total = 0
        candidates = []
        for peer in self._peers_fn():
            tablet = getattr(peer, "tablet", peer)
            try:
                nbytes = tablet.memstore_bytes()
                oldest = tablet.oldest_memstore_write_s()
            except Exception:  # yblint: contained(peer torn down mid-scan — it has no memstore left to count; next arbiter round re-snapshots)
                continue
            total += nbytes
            if nbytes and oldest is not None:
                candidates.append((oldest, nbytes, tablet))
        if total <= limit:
            return 0
        candidates.sort(key=lambda c: c[0])
        flushed = 0
        for oldest, nbytes, tablet in candidates:
            if total <= limit:
                break
            tid = getattr(tablet, "tablet_id", "?")
            TRACE("global memstore %d > %d: flushing tablet %s",
                  total, limit, tid)
            for listener in self.flush_listeners:
                listener(tid)
            tablet.flush()
            total -= nbytes
            flushed += 1
            if self._c_forced is not None:
                self._c_forced.increment()
        return flushed
