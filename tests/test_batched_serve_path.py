"""PR 11 — batched serve path: client batcher semantics, group-commit
writes, follower-read vouching, and the chaos window.

Covers the serve-path contracts:
  - the YBSession batcher coalesces per tablet, fans out concurrently,
    auto-flushes full groups in the background, and demuxes errors
    per op instead of first-error-wins;
  - a multi-op batch replicates as ONE raft entry (group commit) and is
    observable on the serve-path metrics + /servez;
  - batched writes produce results identical to the same ops applied
    sequentially, under MVCC overwrites, column deletes, row
    tombstones and TTL expiry;
  - a leader failover mid-batched-load loses zero acked writes;
  - follower reads refuse replicas without a live digest vouch
    (retryable, so the client's replica walk falls through to the
    leader), serve correctly once the digest exchange vouches them, and
    NEVER surface a raw Corruption.
"""

import threading
import time

import pytest

from yugabyte_tpu.client.session import SessionFlushError, YBSession
from yugabyte_tpu.common.hybrid_time import HybridClock, HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                   MiniClusterOptions)
from yugabyte_tpu.tablet.tablet import Tablet
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.metrics import serve_path_metrics
from yugabyte_tpu.utils.status import Code, StatusError

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING),
             ColumnSchema("n", DataType.INT64)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


def ins(k: str, v: str, n=None, ttl_ms=None) -> QLWriteOp:
    vals = {"v": v}
    if n is not None:
        vals["n"] = n
    return QLWriteOp(WriteOpKind.INSERT, dk(k), vals, ttl_ms=ttl_ms)


@pytest.fixture()
def cluster(tmp_path):
    c = MiniCluster(MiniClusterOptions(
        num_tservers=3, fs_root=str(tmp_path / "cluster"))).start()
    yield c
    c.shutdown()


def _make_table(cluster, name, num_tablets=2):
    client = cluster.new_client()
    client.create_namespace("sp")
    table = client.create_table("sp", name, SCHEMA,
                                num_tablets=num_tablets)
    cluster.wait_for_table_leaders("sp", name)
    return client, table


def _leader_peer(cluster, tablet_id):
    for ts in cluster.tservers:
        if tablet_id in ts.tablet_manager.tablet_ids():
            peer = ts.tablet_manager.get_tablet(tablet_id)
            if peer.raft.is_leader():
                return ts, peer
    return None, None


def _follower_peer(cluster, tablet_id):
    for ts in cluster.tservers:
        if tablet_id in ts.tablet_manager.tablet_ids():
            peer = ts.tablet_manager.get_tablet(tablet_id)
            if not peer.raft.is_leader():
                return ts, peer
    return None, None


# ------------------------------------------------------------- batcher
class TestBatcher:
    def test_flush_coalesces_per_tablet_and_reads_back(self, cluster):
        client, table = _make_table(cluster, "t1")
        s = YBSession(client)
        for i in range(40):
            s.apply(table, ins(f"k{i:03d}", f"v{i}"))
        assert s.flush() == 40
        rows = client.multi_read(table, [dk(f"k{i:03d}")
                                         for i in range(40)])
        assert [r.to_dict(SCHEMA)["v"] for r in rows] == \
            [f"v{i}" for i in range(40)]

    def test_max_batch_background_flush(self, cluster):
        client, table = _make_table(cluster, "t2")
        s = YBSession(client, max_batch_ops=8)
        for i in range(30):
            s.apply(table, ins(f"b{i:03d}", f"v{i}"))
        # full groups went out in the background; flush settles the rest
        s.flush()
        assert not s.has_pending_operations()
        rows = client.multi_read(table, [dk(f"b{i:03d}")
                                         for i in range(30)])
        assert all(r is not None for r in rows)

    def test_per_op_error_demux(self, cluster):
        client, table = _make_table(cluster, "t3", num_tablets=4)
        s = YBSession(client)
        good = [ins(f"g{i}", "ok") for i in range(6)]
        # unknown column: the server rejects this op's GROUP
        # deterministically (schema.column_id KeyError — not retryable)
        bad = QLWriteOp(WriteOpKind.INSERT, dk("g0"), {"nope": 1})
        for op in good:
            s.apply(table, op)
        s.apply(table, bad)
        with pytest.raises(SessionFlushError) as ei:
            s.flush()
        failed_ops = [op for _t, op, _e in ei.value.per_op]
        assert bad in failed_ops
        # only the bad op's tablet group failed — ops routed to OTHER
        # tablets landed (per-op demux, not first-error-wins)
        failed_keys = {op.doc_key for op in failed_ops}
        landed = [op for op in good if op.doc_key not in failed_keys]
        assert landed, "expected at least one group to land"
        rows = client.multi_read(table, [op.doc_key for op in landed])
        assert all(r is not None for r in rows)

    def test_flush_window_timer(self, cluster):
        client, table = _make_table(cluster, "t4")
        s = YBSession(client, flush_interval_s=0.1)
        s.apply(table, ins("w0", "v"))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if client.read_row(table, dk("w0")) is not None \
                    and not s.has_pending_operations():
                break
            time.sleep(0.05)
        assert client.read_row(table, dk("w0")) is not None
        s.close()


# -------------------------------------------------------- group commit
class TestGroupCommit:
    def test_multi_op_batch_is_one_raft_replicate(self, cluster):
        client, table = _make_table(cluster, "gc1", num_tablets=1)
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        _ts, peer = _leader_peer(cluster, tablet_id)
        assert peer is not None
        before_idx = peer.raft.last_op_id[1]
        m = serve_path_metrics()
        before_gc = m.counter("write_group_commit_total").value()
        before_ops = m.counter("write_batch_coalesced_ops_total").value()
        s = YBSession(client)
        for i in range(16):
            s.apply(table, ins(f"gc{i:02d}", "v"))
        s.flush()
        # 16 rows, ONE raft entry appended (one WAL append, one apply)
        assert peer.raft.last_op_id[1] == before_idx + 1
        assert m.counter("write_group_commit_total").value() \
            >= before_gc + 1
        assert m.counter("write_batch_coalesced_ops_total").value() \
            >= before_ops + 16

    def test_servez_endpoint(self, cluster):
        client, table = _make_table(cluster, "gc2")
        s = YBSession(client)
        for i in range(8):
            s.apply(table, ins(f"z{i}", "v"))
        s.flush()
        ts = cluster.tservers[0]
        page = ts.servez()
        assert page["server_id"] == ts.server_id
        assert page["serve_path"]["write_group_commit_total"] >= 1
        assert "write_batch_rows" in page["serve_path"]
        assert all("vouched" in t for t in page["tablets"])

    def test_batched_results_match_sequential(self, tmp_path):
        """The same logical op sequence applied (a) as batches and (b)
        one op per write produces identical resolved rows — under
        overwrites, column deletes, row tombstones and TTL expiry."""
        def script():
            yield [ins(f"s{i}", f"v{i}", n=i) for i in range(8)]
            yield [QLWriteOp(WriteOpKind.UPDATE, dk("s1"), {"v": "v1b"}),
                   QLWriteOp(WriteOpKind.UPDATE, dk("s2"), {"n": 42}),
                   ins("s8", "late")]
            yield [QLWriteOp(WriteOpKind.DELETE_COLS, dk("s3"),
                             columns_to_delete=("v",)),
                   QLWriteOp(WriteOpKind.DELETE_ROW, dk("s4")),
                   QLWriteOp(WriteOpKind.UPDATE, dk("s5"), {"v": None})]
            yield [ins("s4", "reborn"),           # reinsert over tombstone
                   ins("s9", "gone", ttl_ms=1)]   # expires immediately

        clock = HybridClock()
        ta = Tablet("ta", str(tmp_path / "a"), SCHEMA, clock=clock)
        tb = Tablet("tb", str(tmp_path / "b"), SCHEMA, clock=clock)
        for batch in script():
            ta.write(batch)           # ONE write = one group commit
            for op in batch:
                tb.write([op])        # sequential twin
        time.sleep(0.01)  # let the 1ms TTL lapse
        keys = [dk(f"s{i}") for i in range(10)]
        read_ht = clock.now()
        rows_a = ta.multi_read(keys, read_ht)
        rows_b = tb.multi_read(keys, read_ht)

        def norm(rows):
            return [None if r is None
                    else (r.doc_key.encode(), sorted(r.columns.items()))
                    for r in rows]

        assert norm(rows_a) == norm(rows_b)
        # and batched read == sequential reads on the same tablet
        seq = [ta.read_row(k, read_ht) for k in keys]
        assert norm(rows_a) == norm(seq)
        ta.close()
        tb.close()

    def test_leader_failover_mid_batch_zero_acked_loss(self, cluster):
        """The chaos window: batched writers keep flushing while the
        leader tserver restarts; every op whose flush was ACKED must be
        readable afterwards (group commit must not widen the loss
        window)."""
        client, table = _make_table(cluster, "gc3", num_tablets=2)
        acked = {}
        errors = [0]
        stop = threading.Event()

        def writer():
            s = YBSession(client)
            i = 0
            while not stop.is_set():
                batch = {f"f{i + j:05d}": f"v{i + j}" for j in range(10)}
                for k, v in batch.items():
                    s.apply(table, ins(k, v))
                try:
                    s.flush()
                    acked.update(batch)
                except StatusError:
                    errors[0] += 1  # unacked: may or may not have landed
                    time.sleep(0.05)
                i += 10

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(1.5)
        # find and restart the leader of the first tablet (WAL replay +
        # catch-up on the way back)
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        leader_ts, _peer = _leader_peer(cluster, tablet_id)
        assert leader_ts is not None
        idx = cluster.tservers.index(leader_ts)
        cluster.restart_tablet_server(idx)
        time.sleep(2.0)
        stop.set()
        t.join(timeout=30)
        assert len(acked) > 50, "writer made no progress"
        # verify from a FRESH client: every acked write is present with
        # its last-acked value
        fresh = cluster.new_client()
        tbl = fresh.open_table("sp", "gc3")
        keys = sorted(acked)
        rows = fresh.multi_read(tbl, [dk(k) for k in keys])
        missing = [k for k, r in zip(keys, rows) if r is None]
        assert not missing, f"LOST acked rows: {missing[:10]}"
        wrong = [k for k, r in zip(keys, rows)
                 if r.to_dict(SCHEMA)["v"] != acked[k]]
        assert not wrong, f"acked rows with stale values: {wrong[:10]}"


# ------------------------------------------------------ follower reads
class TestFollowerReads:
    def test_unvouched_follower_refuses_retryably(self, cluster):
        client, table = _make_table(cluster, "fr1", num_tablets=1)
        s = YBSession(client)
        for i in range(10):
            s.apply(table, ins(f"r{i}", f"v{i}"))
        s.flush()
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        _ts, follower = _follower_peer(cluster, tablet_id)
        assert follower is not None and not follower.is_vouched()
        before = serve_path_metrics().counter(
            "follower_read_unvouched_rejects_total").value()
        with pytest.raises(StatusError) as ei:
            follower.multi_read([dk("r0")], allow_follower=True)
        assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
        assert ei.value.extra.get("follower_unvouched")
        assert serve_path_metrics().counter(
            "follower_read_unvouched_rejects_total").value() == before + 1
        # the CLIENT path still answers (replica walk falls through to
        # the leader when every follower refuses); wait out the
        # staleness bound so the read point covers the write
        time.sleep(
            flags.get_flag("follower_read_staleness_ms") / 1000 + 0.1)
        row = client.read_row(table, dk("r0"), follower_read=True)
        assert row.to_dict(SCHEMA)["v"] == "v0"

    def test_digest_exchange_vouches_then_follower_serves(self, cluster):
        client, table = _make_table(cluster, "fr2", num_tablets=1)
        s = YBSession(client)
        for i in range(10):
            s.apply(table, ins(f"d{i}", f"v{i}"))
        s.flush()
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        leader_ts, leader = _leader_peer(cluster, tablet_id)
        _fts, follower = _follower_peer(cluster, tablet_id)
        # leader-driven digest exchange: matching followers get vouched
        mismatches = leader_ts._scrub_digest_check(leader)
        assert mismatches == 0
        deadline = time.monotonic() + 10
        while not follower.is_vouched() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert follower.is_vouched()
        # bounded-staleness read point the follower's safe time covers
        read_ht = HybridTime(leader.tablet.mvcc.peek_safe_time().value)
        rows = follower.multi_read([dk(f"d{i}") for i in range(10)],
                                   read_ht, allow_follower=True)
        assert [r.to_dict(SCHEMA)["v"] for r in rows] == \
            [f"v{i}" for i in range(10)]
        # whole-path client follower read agrees (wait out the
        # staleness bound so the read point covers the writes)
        time.sleep(
            flags.get_flag("follower_read_staleness_ms") / 1000 + 0.1)
        got = client.multi_read(table, [dk("d3")], follower_read=True)
        assert got[0].to_dict(SCHEMA)["v"] == "v3"

    def test_vouch_revoked_on_failure_and_ttl(self, cluster):
        client, table = _make_table(cluster, "fr3", num_tablets=1)
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        _ts, follower = _follower_peer(cluster, tablet_id)
        follower.grant_vouch(0)
        assert follower.is_vouched()
        from yugabyte_tpu.utils.status import Status
        follower.mark_failed(Status.IoError("test park"))
        assert not follower.is_vouched()

    def test_follower_read_never_surfaces_raw_corruption(self, cluster):
        """A vouched-but-corrupt follower must answer with a retryable
        ServiceUnavailable (read-path corruption containment), never a
        raw Corruption."""
        import glob
        import os

        from yugabyte_tpu.utils import env as env_mod
        client, table = _make_table(cluster, "fr4", num_tablets=1)
        s = YBSession(client)
        for i in range(50):
            s.apply(table, ins(f"c{i:03d}", "x" * 64))
        s.flush()
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        _ts, follower = _follower_peer(cluster, tablet_id)
        # wait for the follower's apply loop to catch up before flushing
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if follower.tablet.regular_db.approx_entry_count() >= 100:
                break
            time.sleep(0.05)
        follower.tablet.flush()
        # data blocks live in the .sblock sidecar — corrupt THOSE (a
        # corrupt base file fails open loudly at bootstrap, which is its
        # own containment; the read-path case is a bad data block)
        sblocks = glob.glob(os.path.join(
            follower.tablet.regular_db.db_dir, "*.sblock*"))
        assert sblocks
        fts = _ts
        for sb in sblocks:
            env_mod.corrupt_file_range(sb, offset=0, length=1 << 20,
                                       nbits=256)
        # restart the follower's tserver: block/device caches drop, so
        # the next read touches the corrupt bytes physically
        idx = cluster.tservers.index(fts)
        cluster.restart_tablet_server(idx)
        deadline = time.monotonic() + 30
        follower = None
        while time.monotonic() < deadline and follower is None:
            try:
                peer = cluster.tservers[idx].tablet_manager.get_tablet(
                    tablet_id)
                if not peer.raft.is_leader():
                    follower = peer
            except StatusError:
                time.sleep(0.1)
        assert follower is not None
        follower.grant_vouch(0)  # corrupt AND vouched: worst case
        read_ht = HybridTime(
            follower.tablet.mvcc.peek_safe_time().value)
        with pytest.raises(StatusError) as ei:
            follower.multi_read([dk(f"c{i:03d}") for i in range(50)],
                                read_ht, allow_follower=True)
        # contained: retryable, never Code.CORRUPTION
        assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
