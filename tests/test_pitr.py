"""PITR: snapshot schedules + restore to a point in time.

The schedule substrate (catalog run_snapshot_schedules: due snapshots
taken, expired ones pruned — ref master_snapshot_coordinator.cc) and the
restore rule: the EARLIEST snapshot taken at-or-after the target time is
read AT that time — the MVCC history inside the snapshot files
reconstructs the exact state, including rows deleted after the target.
"""

import time

import pytest

from yugabyte_tpu.client.session import YBSession
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                   MiniClusterOptions)
from yugabyte_tpu.tools.yb_admin import AdminClient
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import StatusError

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    old_rf = flags.get_flag("replication_factor")
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("pitr")))).start()
    yield c
    c.shutdown()
    flags.set_flag("replication_factor", old_rf)


def dk(k):
    return DocKey(hash_components=(k,))


def _write(client, table, rows):
    s = YBSession(client)
    for k, v in rows:
        if v is None:
            s.apply(table, QLWriteOp(WriteOpKind.DELETE_ROW, dk(k), {}))
        else:
            s.apply(table, QLWriteOp(WriteOpKind.INSERT, dk(k), {"v": v}))
    s.flush()


def test_restore_to_time(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    table = client.create_table("db", "events", SCHEMA, num_tablets=2)
    cluster.wait_all_replicas_running(table.table_id)
    admin = AdminClient([cluster.master_addrs()[0]])

    _write(client, table, [("a", "v1"), ("b", "v1"), ("doomed", "v1")])
    time.sleep(0.02)
    t1 = int(time.time() * 1e6)          # the restore target
    time.sleep(0.02)
    # post-t1 mutations that the restore must NOT see
    _write(client, table, [("a", "v2"), ("doomed", None), ("new", "v2")])
    admin.create_snapshot("db", "events")   # snapshot AFTER t1: covers it

    admin.restore_to_time("db", "events", t1, "events_at_t1")
    restored = client.open_table("db", "events_at_t1")

    def val(t, k):
        row = client.read_row(t, dk(k))
        if row is None:
            return None
        return list(row.columns.values())[0] if row.columns else None

    assert val(restored, "a") == "v1"        # pre-overwrite value
    assert val(restored, "b") == "v1"
    assert val(restored, "doomed") == "v1"   # deletion undone
    assert val(restored, "new") is None      # post-t1 insert absent
    # live table unchanged
    assert val(table, "a") == "v2"
    assert val(table, "doomed") is None


def test_restore_requires_covering_snapshot(cluster):
    client = cluster.new_client()
    table = client.create_table("db", "nocover", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    admin = AdminClient([cluster.master_addrs()[0]])
    _write(client, table, [("x", "v1")])
    admin.create_snapshot("db", "nocover")
    future = int(time.time() * 1e6) + 60_000_000
    with pytest.raises(StatusError):
        admin.restore_to_time("db", "nocover", future, "nope")


def test_snapshot_schedule_takes_and_prunes(cluster):
    client = cluster.new_client()
    table = client.create_table("db", "sched", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    _write(client, table, [("s", "v")])
    master = cluster.leader_master()
    cat = master.catalog
    # long interval: exactly ONE snapshot is due (taken by our explicit
    # call OR by the master bg loop, whichever runs first — interval 0
    # would race the bg loop into extra snapshots)
    sched = cat.create_snapshot_schedule("db", "sched",
                                         interval_s=3600, retention_s=3600)
    try:
        cat.run_snapshot_schedules()
        deadline = time.time() + 10
        snaps = []
        while time.time() < deadline:
            snaps = [s for s in cat.list_snapshots()
                     if s.get("schedule_id") == sched["schedule_id"]]
            if snaps:
                break
            time.sleep(0.1)
        assert len(snaps) == 1
        assert snaps[0]["snapshot_micros"] > 0
        # shrink retention to zero: next tick prunes it
        sched2 = dict(sched, retention_s=0.0,
                      last_snapshot_unix=time.time() + 3600)
        with cat._lock:
            cat.sys.upsert("snapshot_schedule", sched["schedule_id"], sched2)
        time.sleep(0.01)
        cat.run_snapshot_schedules()
        snaps = [s for s in cat.list_snapshots()
                 if s.get("schedule_id") == sched["schedule_id"]]
        assert snaps == []
    finally:
        cat.delete_snapshot_schedule(sched["schedule_id"])


def test_schedule_survives_in_sys_catalog(cluster):
    master = cluster.leader_master()
    cat = master.catalog
    sched = cat.create_snapshot_schedule("db", "events", 300, 86400)
    try:
        listed = cat.list_snapshot_schedules()
        assert any(s["schedule_id"] == sched["schedule_id"] for s in listed)
    finally:
        cat.delete_snapshot_schedule(sched["schedule_id"])
    assert all(s["schedule_id"] != sched["schedule_id"]
               for s in cat.list_snapshot_schedules())


def test_schedule_retention_reaches_tablets(cluster):
    """PITR history protection: a schedule whose interval exceeds the
    history retention flag must hold tablet history cutoffs back, or
    compaction collapses the MVCC versions a restore needs (ADVICE r3;
    ref tablet_retention_policy.cc AllowedHistoryCutoff)."""
    client = cluster.new_client()
    table = client.create_table("db", "held", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    master = cluster.leader_master()
    cat = master.catalog
    sched = cat.create_snapshot_schedule("db", "held",
                                         interval_s=7200, retention_s=86400)
    covered = set(cat.get_table("db", "held")["tablet_ids"])
    try:
        deadline = time.time() + 10
        held = False
        while time.time() < deadline and not held:
            for ts in cluster.tservers:
                for peer in ts.tablet_manager.peers():
                    t = peer.tablet
                    if (t is not None and peer.tablet_id in covered
                            and t.retention_policy.override_s >= 7200):
                        held = True
            time.sleep(0.1)
        assert held, "retention override never reached the tablet"
        # the held-back cutoff is at least interval_s deep
        cutoff = t.retention_policy.history_cutoff()
        now_us = int(time.time() * 1e6)
        assert cutoff <= (now_us - 7200 * 1_000_000 + 2_000_000) << 12
    finally:
        cat.delete_snapshot_schedule(sched["schedule_id"])
    # deleting the schedule must RELEASE the deep retention (review r4):
    # the next heartbeat's complete map resets uncovered tablets to zero
    deadline = time.time() + 10
    released = False
    while time.time() < deadline and not released:
        released = all(
            peer.tablet.retention_policy.override_s == 0.0
            for ts in cluster.tservers
            for peer in ts.tablet_manager.peers()
            if peer.tablet is not None and peer.tablet_id in covered)
        time.sleep(0.1)
    assert released, "retention override not cleared after schedule delete"


def test_restore_below_history_floor_rejected(cluster):
    """A restore target older than the snapshot's guaranteed MVCC history
    floor must fail loudly instead of returning silently-wrong data."""
    client = cluster.new_client()
    try:
        client.create_namespace("db")
    except StatusError:
        pass  # created by an earlier test in the module-scoped cluster
    table = client.create_table("db", "floorcheck", SCHEMA, num_tablets=1)
    cluster.wait_all_replicas_running(table.table_id)
    master = cluster.leader_master()
    cat = master.catalog
    snap = cat.create_table_snapshot("db", "floorcheck")
    assert "history_floor_micros" in snap
    too_old = snap["history_floor_micros"] - 10_000_000
    with pytest.raises(StatusError) as ei:
        cat.pick_restore_snapshot("db", "floorcheck", too_old)
    assert "history floor" in str(ei.value)
