"""YCSB serve-path soak (tier-2, slow): batched mixes on an RF3
MiniCluster with floor assertions.

The floors are deliberately far under the bench numbers (the PR-11
serve path measures ~2.5-3k ops/s for YCSB-B on a single CI core; the
r07 per-op soak baseline was ~136 ops/s) — they assert the BATCHED
path's step-function advantage survives, not a specific machine's
throughput:

  - YCSB-B (read-heavy through multi_read + batcher group commits)
    sustains >= 4x the old per-op soak baseline,
  - zero acked-write loss: every op whose flush was acked reads back,
  - the scan mix (E) and read-modify-write mix (F) complete with a
    nonzero rate and bounded errors.

Run with: pytest tests/test_ycsb_soak.py -m slow
YBTPU_SOAK_SECONDS scales the per-mix window (default 8s).
"""

import os
import time

import pytest

import yugabyte_tpu.storage.offload_policy  # noqa: F401 — registers flags
from yugabyte_tpu.integration.load_generator import (YCSB_SCHEMA,
                                                     YcsbLoadGenerator)
from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                   MiniClusterOptions)
from yugabyte_tpu.utils import flags

# the r07-era per-op cluster soak measured ~136 ops/s on this cluster
# shape; the batched path must beat it by a wide margin even on a
# loaded single-core CI runner
R07_SOAK_OPS_PER_SEC = 136.0


@pytest.mark.slow
def test_ycsb_mixes_sustain_floor(tmp_path):
    hold = float(os.environ.get("YBTPU_SOAK_SECONDS", 8))
    old = {f: flags.get_flag(f) for f in
           ("device_offload_mode", "point_read_batched",
            "raft_heartbeat_interval_ms",
            "leader_failure_max_missed_heartbeat_periods")}
    # serve-path configuration for an oversubscribed core: native
    # offload (no jax compiles in the serve loop) + relaxed election
    # timing (an unpaced load spike must not read as a dead leader)
    flags.set_flag("device_offload_mode", "native")
    flags.set_flag("point_read_batched", False)
    flags.set_flag("raft_heartbeat_interval_ms", 100)
    flags.set_flag("leader_failure_max_missed_heartbeat_periods", 20)
    cluster = MiniCluster(MiniClusterOptions(
        num_tservers=3, fs_root=str(tmp_path / "cluster"))).start()
    try:
        client = cluster.new_client()
        client.create_namespace("ycsb")
        table = client.create_table("ycsb", "usertable", YCSB_SCHEMA,
                                    num_tablets=4)
        cluster.wait_for_table_leaders("ycsb", "usertable")
        key_space = 4000
        YcsbLoadGenerator(client, table, key_space=key_space).load()
        for ts in cluster.tservers:
            for tid in ts.tablet_manager.tablet_ids():
                ts.tablet_manager.get_tablet(tid).tablet.flush()

        reports = {}
        for mix in ("b", "e", "f"):
            gen = YcsbLoadGenerator(
                client, table, mix=mix, n_threads=2,
                key_space=key_space,
                batch_size=128 if mix == "e" else 512).start()
            time.sleep(hold)
            reports[mix] = gen.stop()

        b = reports["b"]
        assert b.ops >= 1, "YCSB-B made no progress"
        # floor: >= 4x the old per-op soak rate (measured ~20x; floor
        # kept low for noisy single-core CI)
        assert b.ops_per_sec >= 4 * R07_SOAK_OPS_PER_SEC, \
            f"YCSB-B {b.ops_per_sec} ops/s under floor"
        assert b.errors <= b.ops * 0.01
        # scan-heavy mix: scan RPCs completed and returned rows
        e = reports["e"]
        assert e.scans > 0 and e.scan_rows > 0
        # read-modify-write mix made progress with bounded errors
        f = reports["f"]
        assert f.ops_per_sec > R07_SOAK_OPS_PER_SEC
        assert f.errors <= max(4, f.ops * 0.01)

        # zero acked-write loss: the load phase acked every preload
        # key; after three unpaced mixes (updates, scans, RMWs) every
        # one of them must still read back
        import random

        from yugabyte_tpu.docdb.doc_key import DocKey
        rng = random.Random(7)
        sample = sorted({rng.randrange(key_space) for _ in range(512)})
        rows = client.multi_read(
            table, [DocKey(hash_components=(f"u{kid:08d}",))
                    for kid in sample])
        missing = [kid for kid, r in zip(sample, rows) if r is None]
        assert not missing, f"acked preload keys lost: {missing[:10]}"
    finally:
        cluster.shutdown()
        for f_, v in old.items():
            flags.set_flag(f_, v)
