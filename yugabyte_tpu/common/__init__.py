from yugabyte_tpu.common.hybrid_time import HybridTime, DocHybridTime, HybridClock
from yugabyte_tpu.common.schema import Schema, ColumnSchema, DataType
from yugabyte_tpu.common.partition import Partition, PartitionSchema
