"""Differential tests for the pre-sorted-run merge kernel (ops/run_merge.py).

The round-3 compaction hot path: bitonic merge network over K sorted runs +
shared GC filter + packed decision buffer. Every case cross-checks survivors
(in merged order) and make-tombstone decisions against the native C++
baseline (reference architecture: heap merge + sequential filter) and, where
cheap, the radix kernel — three independent implementations must agree.
"""

import numpy as np
import pytest

from yugabyte_tpu.ops import run_merge
from yugabyte_tpu.ops.merge_gc import GCParams, merge_and_gc_device
from yugabyte_tpu.ops.slabs import (
    FLAG_HAS_TTL, FLAG_TOMBSTONE, KVSlab, ValueArray, concat_slabs)
from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline


def _make_run(rng, n, key_space, w=3, tomb_frac=0.1, ttl_frac=0.0,
              ht_lo_bits=20):
    """One sorted run of synthetic entries with duplicate keys across runs."""
    kid = rng.integers(0, key_space, size=n).astype(np.uint32)
    key_words = np.zeros((n, w), dtype=np.uint32)
    key_words[:, 0] = 0x53000000 | (kid >> 16)
    key_words[:, 1] = (kid << 16) | 0x2100
    key_len = np.full(n, 7, dtype=np.int32)   # 7 bytes -> word2 zero pad
    dkl = np.full(n, 7, dtype=np.int32)
    is_col = rng.random(n) < 0.5              # half root writes, half column
    key_words[is_col, 1] |= 0x4B              # 'K' subkey marker byte
    key_len[is_col] = 10
    ht = rng.integers(1, 1 << ht_lo_bits, size=n).astype(np.uint64) << 12
    flags = np.where(rng.random(n) < tomb_frac, FLAG_TOMBSTONE, 0).astype(np.uint32)
    ttl_ms = np.zeros(n, dtype=np.int64)
    if ttl_frac:
        has = rng.random(n) < ttl_frac
        flags[has] |= FLAG_HAS_TTL
        ttl_ms[has] = rng.integers(1, 1000, size=int(has.sum()))
    wid = rng.integers(0, 4, size=n).astype(np.uint32)
    # full internal-key order incl. wid desc: a (key, ht) collision within a
    # run must still leave the run ascending under the merge comparator
    order = np.lexsort((~wid, ~ht, key_len) + tuple(
        key_words[:, j] for j in range(w - 1, -1, -1)))
    return KVSlab(
        key_words=key_words[order], key_len=key_len[order],
        doc_key_len=dkl[order],
        ht_hi=(ht[order] >> 32).astype(np.uint32),
        ht_lo=(ht[order] & 0xFFFFFFFF).astype(np.uint32),
        write_id=wid[order], flags=flags[order], ttl_ms=ttl_ms[order],
        value_idx=np.arange(n, dtype=np.int32),
        values=ValueArray.empty_rows(n))


def _check_against_baseline(runs, cutoff, is_major, retain_deletes=False):
    params = GCParams(cutoff, is_major, retain_deletes)
    perm, keep, mk = run_merge.merge_and_gc_runs(runs, params)
    merged = concat_slabs(runs)
    offsets = np.concatenate(([0], np.cumsum([r.n for r in runs]))).tolist()
    order_c, keep_c, mk_c = compact_cpu_baseline(
        merged, offsets, cutoff, is_major, retain_deletes)
    surv = perm[keep]
    surv_c = order_c[keep_c]
    assert np.array_equal(surv, surv_c), (
        f"survivor mismatch: {len(surv)} vs {len(surv_c)}")
    assert np.array_equal(perm[mk], order_c[mk_c])
    return surv


@pytest.mark.parametrize("k,seed", [(2, 0), (3, 1), (4, 2), (5, 3), (8, 4)])
def test_differential_multi_run(k, seed):
    rng = np.random.default_rng(seed)
    runs = [_make_run(rng, int(rng.integers(50, 400)), key_space=60)
            for _ in range(k)]
    _check_against_baseline(runs, cutoff=(1 << 21) << 12, is_major=True)
    _check_against_baseline(runs, cutoff=(1 << 19) << 12, is_major=False)


def test_single_run_is_gc_only():
    rng = np.random.default_rng(7)
    runs = [_make_run(rng, 300, key_space=40)]
    surv = _check_against_baseline(runs, cutoff=(1 << 19) << 12,
                                   is_major=True)
    assert len(surv) > 0


def test_unequal_run_sizes():
    rng = np.random.default_rng(11)
    runs = [_make_run(rng, n, key_space=100) for n in (1000, 17, 3, 260)]
    _check_against_baseline(runs, cutoff=(1 << 20) << 12, is_major=True)


def test_ttl_expiry_paths():
    rng = np.random.default_rng(13)
    runs = [_make_run(rng, 200, key_space=30, ttl_frac=0.4)
            for _ in range(3)]
    # minor compaction: expired values become tombstones (mk set)
    params = GCParams((1 << 22) << 12, False)
    perm, keep, mk = run_merge.merge_and_gc_runs(runs, params)
    merged = concat_slabs(runs)
    offsets = np.concatenate(([0], np.cumsum([r.n for r in runs]))).tolist()
    order_c, keep_c, mk_c = compact_cpu_baseline(
        merged, offsets, (1 << 22) << 12, False)
    assert np.array_equal(perm[keep], order_c[keep_c])
    assert np.array_equal(perm[mk], order_c[mk_c])
    assert mk.sum() > 0  # the workload must actually exercise expiry
    # major: expired + visible tombstones vanish
    _check_against_baseline(runs, cutoff=(1 << 22) << 12, is_major=True)


def test_retain_deletes():
    rng = np.random.default_rng(17)
    runs = [_make_run(rng, 150, key_space=25, tomb_frac=0.5)
            for _ in range(2)]
    _check_against_baseline(runs, cutoff=(1 << 21) << 12, is_major=True,
                            retain_deletes=True)


def test_matches_radix_kernel():
    """Three-way agreement: run-merge == radix kernel == C++ baseline."""
    rng = np.random.default_rng(23)
    runs = [_make_run(rng, 256, key_space=50) for _ in range(4)]
    cutoff = (1 << 20) << 12
    surv = _check_against_baseline(runs, cutoff, is_major=True)
    merged = concat_slabs(runs)
    perm_r, keep_r, _ = merge_and_gc_device(merged, GCParams(cutoff, True))
    assert np.array_equal(np.sort(surv), np.sort(perm_r[keep_r]))


def test_staged_runs_reuse_matches_fresh_upload():
    """Device-resident path: per-run staged cols re-laid out on device must
    produce identical decisions to a fresh run-major upload."""
    from yugabyte_tpu.ops.merge_gc import stage_slab

    rng = np.random.default_rng(29)
    runs = [_make_run(rng, int(rng.integers(100, 300)), key_space=40)
            for _ in range(3)]
    params = GCParams((1 << 20) << 12, True)
    staged_list = [stage_slab(r) for r in runs]
    staged = run_merge.stage_runs_from_staged(staged_list)
    perm_a, keep_a, mk_a = run_merge.merge_and_gc_runs(
        runs, params, staged=staged)
    perm_b, keep_b, mk_b = run_merge.merge_and_gc_runs(runs, params)
    assert np.array_equal(perm_a[keep_a], perm_b[keep_b])
    assert np.array_equal(perm_a[mk_a], perm_b[mk_b])


def test_write_id_tiebreak():
    """Same key+ht, different write ids: wid descends within the version
    stack and the overwrite check uses it (ref docdb_compaction_filter.cc
    DocHybridTime ordering)."""
    w = 2
    n = 6
    key_words = np.zeros((n, w), dtype=np.uint32)
    key_words[:, 0] = 0x41414141
    key_len = np.array([4, 4, 4, 4, 4, 4], dtype=np.int32)
    dkl = key_len.copy()
    ht = np.array([100, 100, 100, 50, 50, 10], dtype=np.uint64) << 12
    wid = np.array([2, 1, 0, 1, 0, 0], dtype=np.uint32)
    run = KVSlab(key_words=key_words, key_len=key_len, doc_key_len=dkl,
                 ht_hi=(ht >> 32).astype(np.uint32),
                 ht_lo=(ht & 0xFFFFFFFF).astype(np.uint32),
                 write_id=wid, flags=np.zeros(n, np.uint32),
                 ttl_ms=np.zeros(n, np.int64),
                 value_idx=np.arange(n, dtype=np.int32),
                 values=ValueArray.empty_rows(n))
    half = KVSlab(key_words=key_words[::2], key_len=key_len[::2],
                  doc_key_len=dkl[::2],
                  ht_hi=(ht[::2] >> 32).astype(np.uint32),
                  ht_lo=(ht[::2] & 0xFFFFFFFF).astype(np.uint32),
                  write_id=wid[::2], flags=np.zeros(3, np.uint32),
                  ttl_ms=np.zeros(3, np.int64),
                  value_idx=np.arange(3, dtype=np.int32),
                  values=ValueArray.empty_rows(3))
    other = KVSlab(key_words=key_words[1::2], key_len=key_len[1::2],
                   doc_key_len=dkl[1::2],
                   ht_hi=(ht[1::2] >> 32).astype(np.uint32),
                   ht_lo=(ht[1::2] & 0xFFFFFFFF).astype(np.uint32),
                   write_id=wid[1::2], flags=np.zeros(3, np.uint32),
                   ttl_ms=np.zeros(3, np.int64),
                   value_idx=np.arange(3, dtype=np.int32),
                   values=ValueArray.empty_rows(3))
    _check_against_baseline([half, other], cutoff=(200 << 12),
                            is_major=True)
    _check_against_baseline([run], cutoff=(60 << 12), is_major=False)


def test_pallas_failure_degrades_to_network(monkeypatch):
    """A Mosaic lowering/runtime failure on the first real-TPU run must
    degrade to the jnp network, not kill the compaction/bench process."""
    from bench import _split_runs, synth_ycsb_runs
    from yugabyte_tpu.ops import pallas_merge, run_merge
    from yugabyte_tpu.ops.merge_gc import GCParams

    slab, offsets = synth_ycsb_runs(1 << 12, 4, 1 << 11, seed=3)
    staged = run_merge.stage_runs_from_slabs(_split_runs(slab, offsets))
    params = GCParams((10_000_000 << 12), True)
    expect = run_merge.launch_merge_gc(staged, params).result()

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("mosaic lowering exploded")

    monkeypatch.setattr(pallas_merge, "launch_merge_gc_pallas", boom)
    monkeypatch.setattr(run_merge, "_pallas_broken", False)
    monkeypatch.setattr(run_merge, "_pick_impl", lambda s: "pallas")
    got = run_merge.launch_merge_gc(staged, params).result()
    assert calls["n"] == 1
    # process-wide circuit breaker: no second pallas attempt
    got2 = run_merge.launch_merge_gc(staged, params).result()
    assert calls["n"] == 1
    import numpy as np
    for a, b in zip(expect, got):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(expect, got2):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------- chunking

def _chunk_equal(runs, cutoff, is_major, monkeypatch, target,
                 expect_chunked=None):
    """Chunked launch must produce BIT-IDENTICAL (perm, keep, mk) to the
    unchunked launch: chunks are route-partitioned in key order and the
    per-chunk tiebreak preserves run-major order, so even the merged
    order matches exactly."""
    params = GCParams(cutoff, is_major)
    staged = run_merge.stage_runs_from_slabs(runs)
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "0")
    p0, k0, m0 = run_merge.launch_merge_gc(staged, params).result()
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", str(target))
    h = run_merge.launch_merge_gc(staged, params)
    if expect_chunked is not None:
        assert isinstance(h, run_merge._ChunkedMergeGCHandle) \
            == expect_chunked, type(h).__name__
    p1, k1, m1 = h.result()
    assert np.array_equal(p0, p1)
    assert np.array_equal(k0, k1)
    assert np.array_equal(m0, m1)
    return h


@pytest.mark.parametrize("k,seed", [(2, 10), (3, 11), (4, 12)])
def test_chunked_matches_unchunked(k, seed, monkeypatch):
    rng = np.random.default_rng(seed)
    runs = [_make_run(rng, int(rng.integers(1500, 2049)), key_space=500)
            for _ in range(k)]
    h = _chunk_equal(runs, (1 << 19) << 12, True, monkeypatch,
                     target=2048, expect_chunked=True)
    # subcompactions really happened, on bounded shapes
    assert len(h._handles) >= 2
    assert all(hh._staged.m < 2048 for hh in h._handles)


def test_chunked_doc_atomicity_under_hot_docs(monkeypatch):
    """A handful of doc keys with thousands of versions each: route
    boundaries must keep every doc whole (the GC overwrite logic depends
    on it). With this much skew the chunker may legitimately refuse
    (bucket would not shrink) — equality must hold either way."""
    rng = np.random.default_rng(13)
    runs = [_make_run(rng, 2000, key_space=6) for _ in range(4)]
    _chunk_equal(runs, (1 << 19) << 12, True, monkeypatch, target=2048)
    _chunk_equal(runs, (1 << 18) << 12, False, monkeypatch, target=2048)


def test_chunked_against_native_baseline(monkeypatch):
    rng = np.random.default_rng(14)
    runs = [_make_run(rng, 1800, key_space=300, ttl_frac=0.1)
            for _ in range(4)]
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "2048")
    staged = run_merge.stage_runs_from_slabs(runs)
    params = GCParams((1 << 19) << 12, True)
    h = run_merge.launch_merge_gc(staged, params)
    assert isinstance(h, run_merge._ChunkedMergeGCHandle)
    perm, keep, mk = h.result()
    merged = concat_slabs(runs)
    offsets = np.concatenate(([0], np.cumsum([r.n for r in runs]))).tolist()
    order_c, keep_c, mk_c = compact_cpu_baseline(
        merged, offsets, (1 << 19) << 12, True, False)
    assert np.array_equal(perm[keep], order_c[keep_c])
    assert np.array_equal(perm[mk], order_c[mk_c])


def test_chunked_disabled_below_threshold(monkeypatch):
    rng = np.random.default_rng(15)
    runs = [_make_run(rng, 300, key_space=60) for _ in range(4)]
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "1048576")
    staged = run_merge.stage_runs_from_slabs(runs)
    h = run_merge.launch_merge_gc(staged, GCParams((1 << 19) << 12, True))
    assert not isinstance(h, run_merge._ChunkedMergeGCHandle)
