"""sst_dump: inspect one SST file (ref: rocksdb/tools/sst_dump_tool.cc).

    python -m yugabyte_tpu.tools.sst_dump <base.sst> [--entries N] [--blocks]
    python -m yugabyte_tpu.tools.sst_dump <base.sst> --verify

Prints props + frontier (+ block index and sample entries), decoding DocDB
keys into doc-key / subkey / hybrid-time components. --verify runs the
deep integrity check (every block CRC + footer + index/bloom
consistency — the same storage/integrity.py core the background scrubber
uses) and exits non-zero on corruption.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def describe_entry(key_prefix: bytes, dht, value: bytes, flags: int) -> str:
    from yugabyte_tpu.docdb.doc_key import DocKey
    from yugabyte_tpu.docdb.value import Value
    try:
        dk, pos = DocKey.decode(key_prefix)
        sub = f" sub={key_prefix[pos:].hex()}" if pos < len(key_prefix) else ""
        keystr = f"{dk!r}{sub}"
    except Exception:  # noqa: BLE001 — raw fallback for system keys
        keystr = key_prefix.hex()
    try:
        v = Value.decode(value)
        if v.is_tombstone:
            vstr = "<tombstone>"
        elif v.is_object:
            vstr = "<object>"
        else:
            vstr = repr(v.primitive)
        if v.ttl_ms:
            vstr += f" ttl={v.ttl_ms}ms"
    except Exception:  # noqa: BLE001
        vstr = value.hex()
    return (f"{keystr} @ ht={dht.ht.value} wid={dht.write_id} "
            f"flags={flags:#x} -> {vstr}")


def dump(base_path: str, entries: int = 10, blocks: bool = False,
         out=None) -> int:
    from yugabyte_tpu.storage.sst import SSTReader
    out = out or sys.stdout
    r = SSTReader(base_path)
    try:
        p = r.props
        print(f"file:        {base_path}", file=out)
        print(f"entries:     {p.n_entries}", file=out)
        print(f"data_size:   {p.data_size}  base_size: {p.base_size}",
              file=out)
        print(f"first_key:   {p.first_key.hex()}", file=out)
        print(f"last_key:    {p.last_key.hex()}", file=out)
        print(f"frontier:    op_id={p.frontier.op_id_min}-"
              f"{p.frontier.op_id_max} ht=[{p.frontier.ht_min}, "
              f"{p.frontier.ht_max}] cutoff={p.frontier.history_cutoff}",
              file=out)
        if p.max_expire_us:
            print(f"max_expire:  {p.max_expire_us}us (whole-file TTL "
                  f"droppable)", file=out)
        print(f"blocks:      {r.n_blocks}", file=out)
        if blocks:
            for i, (off, size, n) in enumerate(r.block_handles):
                print(f"  block {i}: off={off} size={size} n={n} "
                      f"last={r.index_keys[i].hex()}", file=out)
        shown = 0
        for key_prefix, dht, value, flags in r.iter_entries():
            if shown >= entries:
                break
            print(f"  {describe_entry(key_prefix, dht, value, flags)}",
                  file=out)
            shown += 1
        return 0
    finally:
        r.close()


def verify(base_path: str, out=None) -> int:
    """Deep integrity check of one SST; exit 0 = clean, 1 = corrupt."""
    from yugabyte_tpu.storage.integrity import verify_sst
    out = out or sys.stdout
    rep = verify_sst(base_path)
    print(f"file:     {base_path}", file=out)
    print(f"blocks:   {rep.n_blocks} verified "
          f"({rep.bytes_verified} bytes, {rep.n_entries} entries)",
          file=out)
    for err in rep.errors:
        print(f"  CORRUPT: {err}", file=out)
    print("verify: " + ("OK" if rep.ok
                        else f"{len(rep.errors)} error(s)"), file=out)
    return 0 if rep.ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="sst_dump")
    ap.add_argument("base_path")
    ap.add_argument("--entries", type=int, default=10)
    ap.add_argument("--blocks", action="store_true")
    ap.add_argument("--verify", action="store_true",
                    help="deep-check every block CRC + footer + "
                         "index/bloom consistency; non-zero exit on "
                         "corruption")
    args = ap.parse_args(argv)
    if args.verify:
        return verify(args.base_path)
    return dump(args.base_path, args.entries, args.blocks)


if __name__ == "__main__":
    sys.exit(main())
