"""Guarded-by annotation index: auto-discovery for ybsan.

Reuses the lock-discipline pass's OWN collection logic (same regexes,
same alias handling, same multi-line-assignment tolerance) over the
yugabyte_tpu tree, so the set of attributes the static pass enforces
lexically is exactly the set the runtime detector shadows — the two
checkers can never drift apart on what "annotated" means.

Output: [(module_name, class_qualname, {attr: guard})] for every class
that declares at least one `# guarded-by:` attribute. Module-level
guarded globals are excluded: CPython offers no attribute interception
on modules without replacing the module type, and every module-level
guard in the repo fronts a process singleton whose class is annotated
anyway.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from tools.analysis.core import (DEFAULT_TARGETS, REPO_ROOT,
                                 _collect_files, _parse_context)
from tools.analysis.passes.lock_discipline import (LockDisciplinePass,
                                                   _Scope)


def annotation_index(root: str = REPO_ROOT,
                     targets=DEFAULT_TARGETS
                     ) -> List[Tuple[str, str, Dict[str, str]]]:
    out: List[Tuple[str, str, Dict[str, str]]] = []
    lp = LockDisciplinePass()
    for path, rel in sorted(_collect_files(root, targets)):
        ctx, _errs = _parse_context(path, rel)
        if ctx is None:
            continue
        class_scopes: Dict[ast.ClassDef, _Scope] = {}
        module_scope = _Scope()
        lp._collect(ctx, class_scopes, module_scope)
        if not any(s.guards for s in class_scopes.values()):
            continue
        mod = rel[:-3].replace("/", ".")
        for cls, scope in class_scopes.items():
            if scope.guards:
                out.append((mod, ctx.qualname(cls), dict(scope.guards)))
    return out
