"""Version set + MANIFEST: durable LSM file metadata.

Capability parity with the reference's VersionSet/MANIFEST (ref:
src/yb/rocksdb/db/version_set.cc LogAndApply; InstallCompactionResults
db/compaction_job.cc:894). The manifest is a JSON-lines log of version edits;
recovery replays it. Flushed frontiers persist here too (the WAL-replay
bootstrap reads them back — ref: Tablet::MaxPersistentOpId tablet.cc:2931).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from yugabyte_tpu.storage.sst import Frontier, SSTProps


@dataclass
class FileMeta:
    file_id: int
    path: str
    props: SSTProps
    being_compacted: bool = False

    @property
    def total_size(self) -> int:
        return self.props.data_size + self.props.base_size


class VersionSet:
    def __init__(self, db_dir: str):
        self.db_dir = db_dir
        self.manifest_path = os.path.join(db_dir, "MANIFEST")
        self.files: Dict[int, FileMeta] = {}
        self.next_file_id = 1
        self.flushed_frontier: Optional[Frontier] = None
        self.compactions_installed = 0  # in-memory stat (not persisted)
        self._lock = threading.Lock()

    # -- durability ---------------------------------------------------------
    # Manifest bytes go through the process Env like every other storage
    # file: encryption at rest covers the file catalog too, and the
    # fault-injection env can drop manifest fsyncs — a crash then rolls the
    # version set back in step with the SSTs it references (no frontier
    # edit can outlive the flush data it describes).
    def recover(self) -> None:
        from yugabyte_tpu.utils.env import get_env
        if not os.path.exists(self.manifest_path):
            return
        for line in get_env().read_file(self.manifest_path).splitlines():
            if not line.strip():
                continue
            try:
                edit = json.loads(line)
            except ValueError:
                # torn tail: a crash mid-append left a partial edit — the
                # prefix before it is a complete, consistent version (the
                # WAL torn-tail rule applied to the metadata log)
                break
            self._apply(edit, log=False)

    def _append_manifest(self, edits: List[dict]) -> None:
        """One durable append batch of version edits (ref LogAndApply's
        single manifest write per install)."""
        from yugabyte_tpu.utils.env import get_env
        f = get_env().open_append(self.manifest_path)
        try:
            f.append("".join(json.dumps(e) + "\n" for e in edits).encode())
            f.flush(fsync=True)
        finally:
            f.close()

    def _log_edit(self, edit: dict) -> None:
        self._append_manifest([edit])

    def _apply(self, edit: dict, log: bool = True) -> None:
        kind = edit["kind"]
        if kind == "add":
            props = SSTProps.from_json(edit["props"])
            # Manifest stores paths RELATIVE to db_dir: checkpoints/copies of
            # the directory must resolve to their own files.
            fm = FileMeta(edit["file_id"],
                          os.path.join(self.db_dir, edit["path"]), props)
            self.files[fm.file_id] = fm
            self.next_file_id = max(self.next_file_id, fm.file_id + 1)
        elif kind == "delete":
            self.files.pop(edit["file_id"], None)
        elif kind == "frontier":
            self.flushed_frontier = Frontier.from_json(edit["frontier"])
        if log:
            self._log_edit(edit)

    # -- mutations ----------------------------------------------------------
    def new_file_id(self) -> int:
        with self._lock:
            fid = self.next_file_id
            self.next_file_id += 1
            return fid

    def add_file(self, file_id: int, path: str, props: SSTProps) -> None:
        with self._lock:
            self._apply({"kind": "add", "file_id": file_id,
                         "path": os.path.relpath(path, self.db_dir),
                         "props": props.to_json()})

    def install_compaction(self, removed: List[int], added: List[tuple]) -> None:
        """Atomically (single manifest append batch) swap inputs for outputs."""
        with self._lock:
            edits = [{"kind": "delete", "file_id": fid} for fid in removed]
            edits += [{"kind": "add", "file_id": fid,
                       "path": os.path.relpath(path, self.db_dir),
                       "props": props.to_json()} for fid, path, props in added]
            self._append_manifest(edits)
            for e in edits:
                self._apply(e, log=False)
            self.compactions_installed += 1

    def set_flushed_frontier(self, frontier: Frontier) -> None:
        with self._lock:
            self._apply({"kind": "frontier", "frontier": frontier.to_json()})

    def live_files(self) -> List[FileMeta]:
        with self._lock:
            # newest first (higher file id = newer run) — universal compaction order
            return sorted(self.files.values(), key=lambda f: -f.file_id)
