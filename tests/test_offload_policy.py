"""Offload routing (VERDICT r3 #2): production compactions route device
vs native from LIVE bucket-health measurement, never into a known
pessimization — the policy seam is the BucketHealthBoard
(storage/bucket_health.py), which replaced the static calibration file
in PR 16. These tests cover the policy-site plumbing: the use_device()
gate the compaction job calls, the forced-mode flags, the shared
(k_pad, m) bucket vocabulary, server-context ownership, and the
quarantine registry's restore path."""

import pytest

from yugabyte_tpu.storage import offload_policy
from yugabyte_tpu.storage.bucket_health import BucketHealthBoard, health_board
from yugabyte_tpu.utils import flags

FAM = "run_merge_fused"


@pytest.fixture(autouse=True)
def _clean_board():
    health_board().reset()
    yield
    health_board().reset()


def _warm(board, bucket, device_rate, native_rate):
    board.record_native(FAM, bucket, int(native_rate), 1.0)
    for _ in range(int(flags.get_flag("bucket_health_warmup_obs"))):
        board.record_device(FAM, bucket, int(device_rate), 1.0)
    return board


def test_unobserved_is_native():
    """VERDICT r4 #4 carried forward: without measured proof the device
    never wins a policy decision — a COLD bucket routes native (and its
    compile cost is the prewarm op's to pay, not a live job's)."""
    board = BucketHealthBoard()
    assert not board.use_device(FAM, (4, 2048), est_rows=100_000)
    assert not board.use_device(FAM, (64, 1 << 20), est_rows=10 << 20,
                                cached=True)


def test_measured_pessimization_stays_native():
    # r3's measured reality: device e2e 0.088x native
    board = _warm(BucketHealthBoard(), (64, 1 << 22),
                  device_rate=128_000, native_rate=1_450_000)
    assert board.state(FAM, (64, 1 << 22)) == "degraded"
    # deterministic: demotion stamps the probe clock, so no probe slot
    # opens within the default interval
    assert not board.use_device(FAM, (64, 1 << 22), cached=True)


def test_measured_win_offloads():
    board = _warm(BucketHealthBoard(), (64, 1 << 22),
                  device_rate=5_000_000, native_rate=1_450_000)
    assert board.use_device(FAM, (64, 1 << 22), cached=True)
    # per-bucket rule: a small bucket measured slow stays native while
    # the large winning bucket offloads
    _warm(board, (4, 1 << 14), device_rate=100_000, native_rate=1_000_000)
    assert not board.use_device(FAM, (4, 1 << 14), cached=True)
    assert board.use_device(FAM, (64, 1 << 22), cached=True)


def test_mode_flags_force():
    board = _warm(BucketHealthBoard(), (4, 2048),
                  device_rate=1, native_rate=10)  # measured: device loses
    flags.set_flag("device_offload_mode", "device")
    try:
        assert board.use_device(FAM, (4, 2048))
        assert board.use_device(FAM, (8, 4096))  # even COLD buckets
    finally:
        flags.set_flag("device_offload_mode", "auto")
    flags.set_flag("device_offload_mode", "native")
    try:
        healthy = _warm(BucketHealthBoard(), (4, 2048),
                        device_rate=10, native_rate=1)
        assert not healthy.use_device(FAM, (4, 2048))
    finally:
        flags.set_flag("device_offload_mode", "auto")


def test_bucket_key_vocabulary():
    """The (k_pad, m) vocabulary every dispatch site and the kernel
    manifest agree on: run-major padded layout, power-of-two k."""
    from yugabyte_tpu.ops.run_merge import run_bucket
    assert offload_policy.bucket_key([]) == (0, 0)
    assert offload_policy.bucket_key([100]) == (1, run_bucket(100))
    assert offload_policy.bucket_key([100, 0, 200]) \
        == (2, run_bucket(200))
    assert offload_policy.bucket_key([10, 10, 10, 10, 10])[0] == 8
    assert offload_policy.point_read_bucket_key(4096) == (1, 4096)


def test_compaction_job_cold_routes_native(tmp_path, monkeypatch):
    """run_compaction_job on a COLD (never-measured) bucket must not
    touch the device kernel at all."""
    import jax

    from bench import _attach_values, _split_runs, synth_ycsb_runs
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.storage.compaction import run_compaction_job
    from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter

    n = 4096
    slab, offsets = synth_ycsb_runs(n, 4, n // 2, seed=3)
    _attach_values(slab, 16)
    paths = []
    runs = _split_runs(slab, offsets)
    for i, sub in enumerate(runs):
        p = str(tmp_path / f"{i:06d}.sst")
        SSTWriter(p).write(sub, Frontier())
        paths.append(p)

    def boom(*a, **k):
        raise AssertionError("device kernel invoked on a COLD bucket")
    monkeypatch.setattr(run_merge, "merge_and_gc_runs", boom)
    monkeypatch.setattr(run_merge, "launch_merge_gc", boom)

    board = health_board()
    readers = [SSTReader(p) for p in paths]
    ids = iter(range(1, 100))
    out = tmp_path / "out"
    out.mkdir()
    res = run_compaction_job(readers, str(out), lambda: next(ids),
                             (10_000_000 << 12), True,
                             device=jax.devices()[0],
                             offload_policy=board)
    for r in readers:
        r.close()
    assert res.rows_out > 0
    # the native completion fed the board's LIVE native EWMA, and the
    # bucket is now a prewarm candidate
    qkey = offload_policy.bucket_key(
        run_merge.packed_run_ns([r.n for r in runs]))
    snap = {(k["family"], tuple(k["bucket"])): k
            for k in board.snapshot()["keys"]}
    assert snap[(FAM, qkey)]["native_obs"] >= 1
    assert (FAM, qkey) in board.prewarm_priorities()


def test_compaction_job_measured_demotion_routes_native(
        tmp_path, monkeypatch):
    """A bucket the board measured as a pessimization routes native
    pre-dispatch — no kernel launch, no staging."""
    import jax

    from bench import _attach_values, _split_runs, synth_ycsb_runs
    from yugabyte_tpu.ops import run_merge
    from yugabyte_tpu.storage.compaction import run_compaction_job
    from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter

    n = 4096
    slab, offsets = synth_ycsb_runs(n, 4, n // 2, seed=5)
    _attach_values(slab, 16)
    paths = []
    runs = _split_runs(slab, offsets)
    for i, sub in enumerate(runs):
        p = str(tmp_path / f"{i:06d}.sst")
        SSTWriter(p).write(sub, Frontier())
        paths.append(p)
    qkey = offload_policy.bucket_key(
        run_merge.packed_run_ns([r.n for r in runs]))
    board = health_board()
    _warm(board, qkey, device_rate=1_000, native_rate=1_000_000)
    assert board.state(FAM, qkey) == "degraded"

    def boom(*a, **k):
        raise AssertionError("device kernel invoked on a DEGRADED bucket")
    monkeypatch.setattr(run_merge, "merge_and_gc_runs", boom)
    monkeypatch.setattr(run_merge, "launch_merge_gc", boom)

    readers = [SSTReader(p) for p in paths]
    ids = iter(range(1, 100))
    out = tmp_path / "out"
    out.mkdir()
    res = run_compaction_job(readers, str(out), lambda: next(ids),
                             (10_000_000 << 12), True,
                             device=jax.devices()[0],
                             offload_policy=board)
    for r in readers:
        r.close()
    assert res.rows_out > 0


def test_server_context_owns_board():
    import jax

    from yugabyte_tpu.tserver.server_context import ServerExecutionContext
    ctx = ServerExecutionContext(device=jax.devices()[0])
    try:
        assert ctx.health_board is health_board()
        opts = ctx.tablet_options()
        assert opts.offload_policy is health_board()
    finally:
        ctx.shutdown()


def test_quarantine_registry_is_the_boards():
    """bucket_quarantine() and the board share ONE memory of poisoned
    buckets — a legacy quarantine shows up as board state and decays
    into PROBATION through the board's machinery."""
    q = offload_policy.bucket_quarantine()
    assert q is health_board().quarantine_registry()
    q.quarantine((4, 2048), reason="legacy fault", ttl_s=60.0)
    assert not health_board().allow_device(FAM, (4, 2048))
    assert health_board().state(FAM, (4, 2048)) == "quarantined"
    # snapshot carries the registry entry for /compactionz and /healthz
    snap = health_board().snapshot()
    assert [e for e in snap["quarantine"]
            if tuple(e["bucket"]) == (4, 2048)]


def test_quarantine_restore_reopens_window_without_counter():
    import time

    q = offload_policy.BucketQuarantine()
    added0 = offload_policy._quarantine_counter("added").value()
    q.restore((4, 2048), reason="restored", faults=3, remaining_s=60.0)
    assert q.is_quarantined((4, 2048))
    assert offload_policy._quarantine_counter("added").value() == added0, \
        "a restart is not a new fault: restore must not bump the counter"
    snap = q.snapshot()
    assert snap[0]["faults"] == 3 and snap[0]["reason"] == "restored"
    # a zero-remaining restore decays on first check
    q2 = offload_policy.BucketQuarantine()
    q2.restore((8, 2048), reason="stale", faults=1, remaining_s=0.0)
    time.sleep(0.01)
    assert not q2.is_quarantined((8, 2048))


def test_declared_surface_covers_dispatch_vocabulary():
    """Every family the dispatch sites route through must speak the
    manifest's (k_pad, m) vocabulary (the board keys records by it)."""
    surface = offload_policy.declared_surface_keys()
    if not surface:
        pytest.skip("no committed kernel manifest")
    counts = offload_policy.declared_surface_counts()
    assert counts, "manifest declares families"
    # sanity: manifest keys are (k_pad, m) int pairs
    assert all(len(k) == 2 and all(isinstance(x, int) for x in k)
               for k in surface)
