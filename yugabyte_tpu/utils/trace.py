"""Per-request tracing with cross-node propagation.

Capability parity with yb::Trace (ref: src/yb/util/trace.h:62-137): a Trace
collects timestamped messages for one request; traces dump on slow operations
(ref: LongOperationTracker usage, tserver/read_query.cc:500). A contextvar
carries the current trace, so deep call stacks need no plumbing.

Distributed propagation: every Trace is a SPAN of a distributed trace,
identified by (trace_id, span_id, parent_span_id, sampled). The RPC layer
(rpc/messenger.py) attaches the current span's context to outbound calls and
adopts it on the inbound handler path, so a multi-hop request (client ->
tserver -> raft peers) stitches into one trace_id visible in /tracez. A
Trace opened while another is current inherits that trace's id and parents
itself under it automatically — nested local spans need no plumbing either.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

_current_trace: contextvars.ContextVar[Optional["Trace"]] = contextvars.ContextVar(
    "ybtpu_trace", default=None)

_id_rng = random.Random()


def _new_id(bits: int) -> str:
    return f"{_id_rng.getrandbits(bits):0{bits // 4}x}"


class Trace:
    __slots__ = ("entries", "start", "children", "name", "record",
                 "trace_id", "span_id", "parent_span_id", "sampled",
                 "_token")

    def __init__(self, name: str = "", record: bool = True,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 sampled: bool = True):
        self.entries: List[Tuple[float, str]] = []
        self.start = time.monotonic()
        self.children: List["Trace"] = []
        self.name = name
        # record=False: a child attached to a parent trace — it renders
        # inside the parent's /tracez entry, not as its own
        self.record = record
        # Span identity: explicit ids come from an adopted wire context;
        # otherwise inherit the ambient trace (nested local span) or mint a
        # fresh root trace id.
        if trace_id is None:
            ambient = _current_trace.get()
            if ambient is not None:
                trace_id = ambient.trace_id
                parent_span_id = ambient.span_id
                sampled = ambient.sampled
            else:
                trace_id = _new_id(64)
        self.trace_id = trace_id
        self.span_id = _new_id(32)
        self.parent_span_id = parent_span_id
        self.sampled = sampled

    def message(self, msg: str) -> None:
        self.entries.append((time.monotonic() - self.start, msg))

    def dump(self) -> str:
        lines = [f"{dt * 1e3:10.3f}ms {msg}" for dt, msg in self.entries]
        for child in self.children:
            lines.append("  [child trace]")
            lines.extend("  " + l for l in child.dump().splitlines())
        return "\n".join(lines)

    def wire_context(self) -> Dict[str, object]:
        """The propagation header this span stamps on outbound RPCs
        (rpc/codec.trace_to_wire normalizes it for the wire)."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    @classmethod
    def from_wire_context(cls, ctx: Optional[dict], name: str = "",
                          record: bool = True) -> "Trace":
        """Adopt an inbound RPC's trace header: the new span continues the
        sender's trace_id and parents under the sender's span. A missing /
        malformed header (old peer) starts a fresh root trace."""
        if not isinstance(ctx, dict) or not ctx.get("trace_id"):
            return cls(name, record=record)
        return cls(name, record=record, trace_id=str(ctx["trace_id"]),
                   parent_span_id=(str(ctx["span_id"])
                                   if ctx.get("span_id") else None),
                   sampled=bool(ctx.get("sampled", True)))

    def __enter__(self) -> "Trace":
        self._token = _current_trace.set(self)
        return self

    def __exit__(self, *exc) -> None:
        _current_trace.reset(self._token)
        # children count as content: a request whose only activity is a
        # nested local-bypass call must still appear in /tracez
        if self.record and self.sampled and (self.entries or self.children):
            _record_tracez(self)


def TRACE(msg: str, *args) -> None:
    """Append to the current request trace, if any (ref: TRACE() macro, trace.h)."""
    t = _current_trace.get()
    if t is not None:
        t.message(msg % args if args else msg)


def current_trace() -> Optional[Trace]:
    return _current_trace.get()


def current_trace_context() -> Optional[Dict[str, object]]:
    """Wire context of the current span, or None outside any trace."""
    t = _current_trace.get()
    return t.wire_context() if t is not None else None


# ------------------------------------------------------------- /tracez
# Ring of recently completed traces (ref: the reference's /tracez page
# over yb::Trace sampling). Completed scoped Traces with any entries
# land here; the webserver serves them as JSON.
_tracez_lock = threading.Lock()
_TRACEZ: List[dict] = []
_TRACEZ_CAP = 256


def _span_entry(t: Trace, duration_ms: Optional[float] = None) -> dict:
    if duration_ms is None:
        duration_ms = round((time.monotonic() - t.start) * 1e3, 3)
    return {"name": t.name or "request",
            "wall_ts": time.time(),
            "duration_ms": duration_ms,
            "trace_id": t.trace_id,
            "span_id": t.span_id,
            "parent_span_id": t.parent_span_id,
            "dump": t.dump()}


def _record_tracez(t: Trace) -> None:
    entry = _span_entry(t)
    with _tracez_lock:
        _TRACEZ.append(entry)
        if len(_TRACEZ) > _TRACEZ_CAP:
            del _TRACEZ[: len(_TRACEZ) - _TRACEZ_CAP]


def tracez() -> List[dict]:
    with _tracez_lock:
        return list(reversed(_TRACEZ))


def tracez_grouped() -> List[dict]:
    """Spans grouped by trace_id with per-hop timings — the multi-hop view
    of /tracez: one entry per distributed trace, its spans (hops) oldest
    first, so a slow client -> tserver -> raft-peer write reads as one
    tree instead of fragments on every server."""
    groups: Dict[str, List[dict]] = {}
    order: List[str] = []
    for span in reversed(tracez()):        # oldest first within a trace
        tid = span.get("trace_id") or "untraced"
        if tid not in groups:
            groups[tid] = []
            order.append(tid)
        groups[tid].append(span)
    out = []
    for tid in order:
        spans = groups[tid]
        out.append({
            "trace_id": tid,
            "n_spans": len(spans),
            "wall_ts": spans[0]["wall_ts"],
            "total_duration_ms": round(
                sum(s["duration_ms"] for s in spans), 3),
            "spans": [{k: s[k] for k in
                       ("name", "wall_ts", "duration_ms", "span_id",
                        "parent_span_id", "dump")} for s in spans],
        })
    out.reverse()                          # newest trace first
    return out


def tracez_page() -> dict:
    """The /tracez payload: flat span ring + the grouped-by-trace view."""
    return {"spans": tracez(), "traces": tracez_grouped()}


def threadz() -> List[dict]:
    """Live thread stack dump (the reference exposes /pprof + /threadz
    from the stack-trace collector, util/debug-util.cc)."""
    import sys
    import threading as _t
    import traceback
    frames = sys._current_frames()
    out = []
    for th in _t.enumerate():
        fr = frames.get(th.ident)
        out.append({
            "name": th.name,
            "ident": th.ident,
            "daemon": th.daemon,
            "stack": traceback.format_stack(fr) if fr is not None else [],
        })
    return out


class LongOperationTracker:
    """Warns (collects) when an operation exceeds a threshold (ref:
    util/long_operation_tracker.h). On exceed it TRACEs into the current
    request trace AND dumps the stitched trace-so-far into the /tracez
    ring as a `slow-op:<name>` span, so a slow WAL fsync or raft
    replication is explainable after the fact even if the enclosing
    request ultimately succeeds."""

    def __init__(self, name: str, threshold_ms: float = 1000.0):
        self.name = name
        self.threshold_ms = threshold_ms

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        elapsed_ms = (time.monotonic() - self._start) * 1e3
        if elapsed_ms > self.threshold_ms:
            TRACE("LongOperation %s took %.1fms (threshold %.1fms)",
                  self.name, elapsed_ms, self.threshold_ms)
            self._dump_slow_op(elapsed_ms)

    def _dump_slow_op(self, elapsed_ms: float) -> None:
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        ROOT_REGISTRY.entity("server", "slow_ops").counter(
            "long_operation_exceeded_total",
            "operations that overran their LongOperationTracker "
            "threshold").increment()
        t = _current_trace.get()
        entry = {"name": f"slow-op:{self.name}",
                 "wall_ts": time.time(),
                 "duration_ms": round(elapsed_ms, 3),
                 # a child span of the still-open enclosing request span,
                 # so the grouped view hangs the dump under the right hop
                 "trace_id": t.trace_id if t is not None else _new_id(64),
                 "span_id": _new_id(32),
                 "parent_span_id": t.span_id if t is not None else None,
                 "dump": t.dump() if t is not None else ""}
        with _tracez_lock:
            _TRACEZ.append(entry)
            if len(_TRACEZ) > _TRACEZ_CAP:
                del _TRACEZ[: len(_TRACEZ) - _TRACEZ_CAP]
