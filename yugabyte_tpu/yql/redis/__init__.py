from yugabyte_tpu.yql.redis.server import RedisServer

__all__ = ["RedisServer"]
