// CPU compaction baseline: the reference architecture, faithfully.
//
// Implements the stock CompactionJob hot path the way the reference does it
// (ref: src/yb/rocksdb/db/compaction_job.cc:442 CompactionJob::Run):
//   - k-way merge via a binary min-heap over pre-sorted runs
//     (ref: table/merger.cc:51 MergingIterator)
//   - sequential per-entry MVCC GC filter with the overwrite / TTL /
//     tombstone rules (ref: docdb/docdb_compaction_filter.cc:74-320)
// Single thread = one subcompaction, exactly like the reference
// (compaction_job.cc:456-468 runs one thread per key range).
//
// Exposed as a C ABI for ctypes; used by bench.py as the vs_baseline
// denominator and by tests as a third differential implementation.
//
// Build: g++ -O3 -shared -fPIC -o libcompaction_baseline.so compaction_baseline.cc

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Ctx {
  const uint8_t* keys;
  const int32_t* key_len;
  int32_t stride;
  const uint64_t* ht;
  const uint32_t* wid;
};

// internal-key comparator: key memcmp asc, then ht desc, then wid desc
inline int cmp_entries(const Ctx& c, int64_t a, int64_t b) {
  const uint8_t* ka = c.keys + a * c.stride;
  const uint8_t* kb = c.keys + b * c.stride;
  int32_t la = c.key_len[a], lb = c.key_len[b];
  int32_t m = la < lb ? la : lb;
  int r = memcmp(ka, kb, m);
  if (r) return r;
  if (la != lb) return la < lb ? -1 : 1;
  if (c.ht[a] != c.ht[b]) return c.ht[a] > c.ht[b] ? -1 : 1;  // desc
  if (c.wid[a] != c.wid[b]) return c.wid[a] > c.wid[b] ? -1 : 1;
  return 0;
}

}  // namespace

extern "C" {

// Returns number of kept entries. order_out receives the merged order
// (indices into the flat arrays); keep_out/mk_out are per merged position.
int64_t compact_baseline(
    int32_t n_runs, const int64_t* run_offsets,  // [n_runs+1]
    int64_t n, int32_t stride,
    const uint8_t* keys, const int32_t* key_len, const int32_t* dkl,
    const uint64_t* ht, const uint32_t* wid,
    const uint8_t* flags,  // bit0 tombstone, bit1 obj init, bit2 has-ttl
    const int64_t* ttl_ms,
    uint64_t cutoff_ht, int32_t is_major, int32_t retain_deletes,
    uint8_t* keep_out, uint8_t* mk_out, int64_t* order_out) {
  Ctx c{keys, key_len, stride, ht, wid};

  // ---- binary min-heap of run heads (MergingIterator) --------------------
  std::vector<int64_t> heap;      // entry index
  std::vector<int32_t> heap_run;  // owning run
  std::vector<int64_t> pos(n_runs);
  heap.reserve(n_runs);
  auto heap_less = [&](size_t i, size_t j) {
    return cmp_entries(c, heap[i], heap[j]) < 0;
  };
  auto sift_up = [&](size_t i) {
    while (i > 0) {
      size_t p = (i - 1) / 2;
      if (heap_less(i, p)) {
        std::swap(heap[i], heap[p]);
        std::swap(heap_run[i], heap_run[p]);
        i = p;
      } else break;
    }
  };
  auto sift_down = [&](size_t i) {
    size_t sz = heap.size();
    for (;;) {
      size_t l = 2 * i + 1, r = l + 1, s = i;
      if (l < sz && heap_less(l, s)) s = l;
      if (r < sz && heap_less(r, s)) s = r;
      if (s == i) break;
      std::swap(heap[i], heap[s]);
      std::swap(heap_run[i], heap_run[s]);
      i = s;
    }
  };
  for (int32_t r = 0; r < n_runs; ++r) {
    pos[r] = run_offsets[r];
    if (pos[r] < run_offsets[r + 1]) {
      heap.push_back(pos[r]);
      heap_run.push_back(r);
      sift_up(heap.size() - 1);
    }
  }

  // ---- sequential GC filter state ---------------------------------------
  const uint64_t cutoff_phys = cutoff_ht >> 12;
  int64_t prev = -1;           // previous merged entry
  bool seen_visible = false;   // a <=cutoff version already kept for cur key
  int64_t cur_doc = -1;        // entry whose doc prefix defines current doc
  bool ov_set = false;
  uint64_t ov_ht = 0;
  uint32_t ov_wid = 0;

  int64_t out = 0, kept = 0;
  while (!heap.empty()) {
    int64_t e = heap[0];
    int32_t run = heap_run[0];
    // advance the winning run (pop + push next = replace top + sift)
    if (++pos[run] < run_offsets[run + 1]) {
      heap[0] = pos[run];
      sift_down(0);
    } else {
      heap[0] = heap.back();
      heap_run[0] = heap_run.back();
      heap.pop_back();
      if (!heap.empty()) sift_down(0);
    }

    const uint8_t* k = keys + e * stride;
    int32_t len = key_len[e], d = dkl[e];
    bool same_key = prev >= 0 && key_len[prev] == len &&
                    memcmp(keys + prev * stride, k, len) == 0;
    if (!same_key) seen_visible = false;
    bool same_doc = cur_doc >= 0 && dkl[cur_doc] == d &&
                    memcmp(keys + cur_doc * stride, k, d) == 0;
    if (!same_doc) {
      cur_doc = e;
      ov_set = false;
    }
    prev = e;

    bool below = ht[e] <= cutoff_ht;
    bool visible = false;
    if (below) {
      if (seen_visible) {
        order_out[out] = e; keep_out[out] = 0; mk_out[out] = 0; ++out;
        continue;  // shadowed old version (docdb_compaction_filter.cc:166)
      }
      seen_visible = true;
      visible = true;
    }
    bool is_root = len == d;
    if (is_root && visible && !ov_set) {
      ov_set = true;           // root version visible at cutoff: overwrites subtree
      ov_ht = ht[e];
      ov_wid = wid[e];
    }
    if (!is_root && ov_set &&
        (ht[e] < ov_ht || (ht[e] == ov_ht && wid[e] <= ov_wid))) {
      order_out[out] = e; keep_out[out] = 0; mk_out[out] = 0; ++out;
      continue;  // covered by root overwrite (overwrite-stack truncation)
    }
    bool has_ttl = flags[e] & 4;
    bool expired = has_ttl &&
        ((ht[e] >> 12) + (uint64_t)ttl_ms[e] * 1000 <= cutoff_phys);
    bool already_tomb = flags[e] & 1;
    bool tomb = already_tomb || (expired && below);
    if (below && visible && tomb && is_major && !retain_deletes) {
      order_out[out] = e; keep_out[out] = 0; mk_out[out] = 0; ++out;
      continue;  // visible tombstone at bottommost level (ref :316-319)
    }
    order_out[out] = e;
    keep_out[out] = 1;
    mk_out[out] = (expired && below && !already_tomb && !is_major) ? 1 : 0;
    ++out;
    ++kept;
  }
  return kept;
}

}  // extern "C"
