"""Native run cache: zero-decode steady-state compaction inputs.

storage/run_cache.py + ce_runcache_* (native/compaction_engine.cc): a
flush/compaction output exported into the cache must be byte-equivalent
to re-decoding the file that was written for the same survivor range —
a job ingesting cached runs (prepare_cached) must produce outputs
byte-identical to one decoding the same inputs from disk, including
rewritten-as-tombstone survivors. The cache is an LRU over immutable
C++-side entries; Python's accounting must track the native registry.

ref (what the fast path skips): rocksdb/db/compaction_job.cc:442 input
iteration + table/block-based reader decode per job.
"""

import glob
import os

import numpy as np
import pytest

from yugabyte_tpu.ops.slabs import ValueArray
from yugabyte_tpu.storage import compaction as compaction_mod
from yugabyte_tpu.storage import native_engine
from yugabyte_tpu.storage.device_cache import DeviceSlabCache
from yugabyte_tpu.storage.run_cache import (NamespacedRunCache,
                                            NativeRunCache)
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter

pytestmark = pytest.mark.skipif(not native_engine.available(),
                                reason="native engine unavailable")


def _mk_run(rng, n, key_space, value_bytes=16, ttl_frac=0.0):
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_run_merge import _make_run
    slab = _make_run(rng, n, key_space, ttl_frac=ttl_frac)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _write_runs(workdir, runs):
    readers = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p).write(slab, Frontier())
        readers.append(SSTReader(p))
    return readers


def _export_inputs(rc, input_ids, readers):
    """What flush write-through does: retain each input decoded."""
    from yugabyte_tpu.storage.run_cache import export_reader
    for fid, r in zip(input_ids, readers):
        export_reader(rc, fid, r)


def _device():
    import jax
    return jax.devices()[0]


@pytest.fixture
def workload(tmp_path):
    rng = np.random.default_rng(7)
    runs = [_mk_run(rng, 800, 500, ttl_frac=0.3) for _ in range(4)]
    readers = _write_runs(str(tmp_path), runs)
    yield str(tmp_path), readers
    for r in readers:
        r.close()


def _run_job(readers, out_dir, cutoff, first_id, *, is_major=True,
             cache=None, input_ids=None, run_cache=None):
    os.makedirs(out_dir, exist_ok=True)
    ids = iter(range(first_id, first_id + 500))
    return compaction_mod.run_compaction_job_device_native(
        readers, out_dir, lambda: next(ids), cutoff, is_major,
        device=_device(), device_cache=cache, input_ids=input_ids,
        run_cache=run_cache)


def _data_bytes(out_dir):
    return [open(p, "rb").read()
            for p in sorted(glob.glob(os.path.join(out_dir, "*.data")))]


def test_cached_job_matches_decode_job(workload):
    """All-cached input path == from-disk path, byte for byte."""
    workdir, readers = workload
    cutoff = 1 << 60
    cache = DeviceSlabCache(device=_device())
    input_ids = [10**9 + i for i in range(len(readers))]
    for fid, r in zip(input_ids, readers):
        cache.stage(fid, r.read_all())
    rc = NamespacedRunCache(NativeRunCache(capacity_bytes=1 << 30), "t")
    _export_inputs(rc, input_ids, readers)

    res_rc = _run_job(readers, os.path.join(workdir, "a"), cutoff, 100,
                      cache=cache, input_ids=input_ids, run_cache=rc)
    res_no = _run_job(readers, os.path.join(workdir, "b"), cutoff, 600,
                      cache=cache, input_ids=input_ids, run_cache=None)
    assert res_rc.rows_out == res_no.rows_out
    assert _data_bytes(os.path.join(workdir, "a")) == \
        _data_bytes(os.path.join(workdir, "b"))
    assert rc.hits >= len(readers)


def test_tombstone_rewrite_survives_chain(workload):
    """Survivors rewritten as tombstones (TTL-expired, non-major) must
    round-trip the cache as tombstones: a chained second compaction from
    cached outputs equals one from decoded outputs."""
    workdir, readers = workload
    cutoff = 1 << 62  # far future: TTLs expire -> mk rewrites on non-major
    cache = DeviceSlabCache(device=_device())
    input_ids = [10**9 + i for i in range(len(readers))]
    for fid, r in zip(input_ids, readers):
        cache.stage(fid, r.read_all())
    rc = NamespacedRunCache(NativeRunCache(capacity_bytes=1 << 30), "t")
    _export_inputs(rc, input_ids, readers)

    out1 = os.path.join(workdir, "chain1")
    res1 = _run_job(readers, out1, cutoff, 100, is_major=False,
                    cache=cache, input_ids=input_ids, run_cache=rc)
    outs1 = sorted(glob.glob(os.path.join(out1, "*.sst")))
    assert outs1 and res1.rows_out
    out_ids = [fid for fid, _b, _p in res1.outputs]
    assert all(rc.contains(fid) for fid in out_ids), \
        "compaction outputs must be exported to the run cache"

    # chained second job: cached outputs vs re-decoded outputs
    readers1 = [SSTReader(p) for p in outs1]
    res_c = _run_job(readers1, os.path.join(workdir, "chain2c"), cutoff,
                     300, is_major=True, cache=cache, input_ids=out_ids,
                     run_cache=rc)
    res_d = _run_job(readers1, os.path.join(workdir, "chain2d"), cutoff,
                     700, is_major=True, cache=cache, input_ids=out_ids,
                     run_cache=None)
    for r in readers1:
        r.close()
    assert res_c.rows_out == res_d.rows_out
    assert _data_bytes(os.path.join(workdir, "chain2c")) == \
        _data_bytes(os.path.join(workdir, "chain2d"))


def test_partial_hit_falls_back_to_decode(workload):
    """A single missing input drops the whole job to the file path (run
    order could not otherwise match the device's run-major indexes)."""
    workdir, readers = workload
    cutoff = 1 << 60
    cache = DeviceSlabCache(device=_device())
    input_ids = [10**9 + i for i in range(len(readers))]
    for fid, r in zip(input_ids, readers):
        cache.stage(fid, r.read_all())
    rc = NamespacedRunCache(NativeRunCache(capacity_bytes=1 << 30), "t")
    _export_inputs(rc, input_ids[:-1], readers[:-1])  # one input missing

    res = _run_job(readers, os.path.join(workdir, "p"), cutoff, 100,
                   cache=cache, input_ids=input_ids, run_cache=rc)
    res_no = _run_job(readers, os.path.join(workdir, "q"), cutoff, 600,
                      cache=cache, input_ids=input_ids, run_cache=None)
    assert res.rows_out == res_no.rows_out
    assert _data_bytes(os.path.join(workdir, "p")) == \
        _data_bytes(os.path.join(workdir, "q"))


def test_lru_eviction_and_native_accounting():
    """Eviction keeps Python and C++ byte accounting in step; dropped ids
    are gone from the native registry."""
    rng = np.random.default_rng(3)
    import tempfile
    workdir = tempfile.mkdtemp()
    runs = [_mk_run(rng, 300, 200) for _ in range(3)]
    readers = _write_runs(workdir, runs)
    ids = []
    sizes = []
    for r in readers:
        with native_engine.NativeCompactionJob() as j:
            with open(r.data_path, "rb") as f:
                j.add_input(f.read(), r.block_handles)
            n = j.prepare()
            j.sort_all()
            rid = j.export_run(0, n, b"X")
            ids.append(rid)
            sizes.append(native_engine.runcache_entry_bytes(rid))
    base = native_engine.runcache_bytes()
    # capacity for ~2 entries: inserting all 3 evicts the oldest
    rc = NativeRunCache(capacity_bytes=sizes[0] + sizes[1] + 1)
    for i, (rid, nb) in enumerate(zip(ids, sizes)):
        rc.put(("t", i), rid, nb)
    assert not rc.contains(("t", 0)) and rc.contains(("t", 2))
    assert rc.used_bytes <= rc.capacity
    assert native_engine.runcache_entry_bytes(ids[0]) == -1  # dropped
    rc.drop_namespace("t")
    assert rc.used_bytes == 0
    assert native_engine.runcache_bytes() == base - sum(sizes)
    # an entry larger than the whole budget is evicted immediately — the
    # cache never pins RAM past its cap
    rc2 = NativeRunCache(capacity_bytes=sizes[2] - 1)
    rc2.put(("t", 9), ids[2], sizes[2])
    assert not rc2.contains(("t", 9)) and rc2.used_bytes == 0
    for r in readers:
        r.close()


def test_db_flush_exports_and_compaction_hits(tmp_path):
    """DB integration: flushes export to the run cache, the compaction
    over them starts all-cached (hits == input count), its outputs are
    re-exported, and reads stay correct afterwards."""
    from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
    from yugabyte_tpu.storage.db import DB, DBOptions

    opts = DBOptions(auto_compact=False, device=_device(),
                     device_cache=DeviceSlabCache(device=_device()))
    db = DB(str(tmp_path / "db"), opts)
    if db._run_cache is None:
        db.close()
        pytest.skip("run cache disabled in this configuration")
    n_flushes = 4  # >= universal_compaction_min_merge_width
    expected = {}
    ht = 1000
    for batch in range(n_flushes):
        items = []
        for i in range(200):
            k = b"k%04d" % ((batch * 150 + i) % 400)
            v = b"v%d-%d" % (batch, i)
            items.append((k, DocHybridTime(HybridTime(ht << 12), 0), v))
            expected[k] = v
            ht += 1
        db.write_batch(items)
        fid = db.flush()
        assert db._run_cache.contains(fid), \
            "flush must write through to the run cache"
    hits0 = db._run_cache.hits
    assert db.maybe_schedule_compaction()
    assert db._run_cache.hits >= hits0 + n_flushes, \
        "compaction over flushed SSTs must take the all-cached path"
    live = list(db.versions.files)
    assert all(db._run_cache.contains(fid) for fid in live), \
        "compaction outputs must be re-exported"
    for k, v in list(expected.items())[::17]:
        got = db.get(k)
        assert got is not None and got[1] == v, k
    db.close()
