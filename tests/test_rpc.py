"""RPC layer tests: codec round-trips, messenger calls, error mapping,
concurrency, and a 3-peer Raft group replicating over real loopback sockets
(the reference exercises the same path in rpc/rpc-test.cc and
consensus/raft_consensus-itest)."""

import threading
import time

import pytest

from yugabyte_tpu.rpc.codec import dumps, loads
from yugabyte_tpu.rpc.messenger import (
    Messenger, Proxy, RemoteError, RpcTimeout, ServiceUnavailable)
from yugabyte_tpu.utils.status import Code, Status, StatusError


@pytest.mark.parametrize("obj", [
    None, True, False, 0, 1, -1, 2**64, -(2**70), 3.5, b"", b"\x00\xff" * 10,
    "", "héllo", [], [1, [2, [3]]], {}, {"a": 1, "b": [b"x", None]},
    {1: "int-key", b"b": "bytes-key"},
    {"nested": {"deep": {"deeper": [1.5, True, b"\x80"]}}},
])
def test_codec_roundtrip(obj):
    assert loads(dumps(obj)) == obj


def test_codec_tuple_becomes_list():
    assert loads(dumps((1, 2))) == [1, 2]


def test_codec_rejects_unknown_type():
    with pytest.raises(TypeError):
        dumps(object())


class EchoService:
    def echo(self, x):
        return x

    def add(self, a, b):
        return a + b

    def fail_status(self):
        raise StatusError(Status.NotFound("no such thing"))

    def fail_raise(self):
        raise ValueError("boom")

    def slow(self, delay_s):
        time.sleep(delay_s)
        return "done"


@pytest.fixture
def pair():
    server = Messenger("server")
    server.register_service("echo", EchoService())
    client = Messenger("client")
    yield server, client
    client.shutdown()
    server.shutdown()


def test_basic_call(pair):
    server, client = pair
    assert client.call(server.address, "echo", "add", a=2, b=3) == 5
    assert client.call(server.address, "echo", "echo",
                       x={"k": [b"v", 1]}) == {"k": [b"v", 1]}


def test_proxy(pair):
    server, client = pair
    proxy = Proxy(client, server.address, "echo")
    assert proxy.add(a=10, b=20) == 30


def test_local_bypass(pair):
    server, _ = pair
    # A call addressed to the messenger itself never touches a socket.
    assert server.call(server.address, "echo", "add", a=1, b=1) == 2


def test_status_error_crosses_wire(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "fail_status")
    assert ei.value.status.code == Code.NOT_FOUND


def test_exception_maps_to_remote_error(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "fail_raise")
    assert ei.value.status.code == Code.REMOTE_ERROR
    assert "boom" in ei.value.status.message


def test_unknown_service_and_method(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "nope", "x")
    assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "nope")
    assert ei.value.status.code == Code.NOT_SUPPORTED


def test_timeout_and_connection_survives(pair):
    server, client = pair
    with pytest.raises(RpcTimeout):
        client.call(server.address, "echo", "slow", timeout_s=0.2, delay_s=5)
    # The connection keeps working for later calls.
    assert client.call(server.address, "echo", "add", a=1, b=2) == 3


def test_unreachable_server():
    client = Messenger("client")
    try:
        with pytest.raises(ServiceUnavailable):
            client.call("127.0.0.1:1", "echo", "echo", x=1)
    finally:
        client.shutdown()


def test_concurrent_calls_multiplex(pair):
    server, client = pair
    results = []
    errors = []

    def worker(i):
        try:
            results.append(client.call(server.address, "echo", "add",
                                       a=i, b=i))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert sorted(results) == [2 * i for i in range(32)]


def test_server_shutdown_fails_pending(pair):
    server, client = pair
    done = threading.Event()
    caught = []

    def worker():
        try:
            client.call(server.address, "echo", "slow", timeout_s=10,
                        delay_s=30)
        except (ServiceUnavailable, RpcTimeout) as e:
            caught.append(e)
        done.set()

    threading.Thread(target=worker, daemon=True).start()
    time.sleep(0.2)
    server.shutdown()
    assert done.wait(timeout=5)
    assert caught


# --------------------------------------------------------------- Raft on RPC

def test_raft_over_rpc(tmp_path):
    from yugabyte_tpu.consensus.log import Log
    from yugabyte_tpu.consensus.raft import (
        OP_WRITE, RaftConfig, RaftConsensus)
    from yugabyte_tpu.rpc.consensus_service import RpcTransport

    peers = ["a", "b", "c"]
    messengers = {p: Messenger(p) for p in peers}
    addr_map = {f"{p}/t1": messengers[p].address for p in peers}
    transports = {p: RpcTransport(messengers[p], addr_map.get)
                  for p in peers}

    applied = {p: [] for p in peers}
    nodes = {}
    for p in peers:
        d = tmp_path / p
        d.mkdir()
        cfg = RaftConfig(peer_id=f"{p}/t1",
                         peer_ids=tuple(f"{q}/t1" for q in peers))
        node = RaftConsensus(
            cfg, Log(str(d / "wal")), transports[p],
            apply_cb=lambda m, p=p: applied[p].append(m.payload),
            meta_path=str(d / "meta.json"))
        transports[p].register(cfg.peer_id, node)
        nodes[p] = node

    try:
        nodes["a"].start(election_timer=False)
        nodes["a"].start_election(ignore_lease=True)
        deadline = time.monotonic() + 10
        while not nodes["a"].is_leader():
            assert time.monotonic() < deadline, "leader election stalled"
            time.sleep(0.01)
        for i in range(20):
            nodes["a"].replicate(OP_WRITE, i + 1, b"payload-%d" % i,
                                 timeout_s=10)
        deadline = time.monotonic() + 10
        while any(len(applied[p]) < 20 for p in peers):
            assert time.monotonic() < deadline, \
                f"replication stalled: { {p: len(applied[p]) for p in peers} }"
            time.sleep(0.01)
        for p in peers:
            assert applied[p] == [b"payload-%d" % i for i in range(20)]
    finally:
        for node in nodes.values():
            node.shutdown()
        for m in messengers.values():
            m.shutdown()
