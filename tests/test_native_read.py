"""Differential tests: native read engine vs the Python oracle paths.

The native engine (native/read_engine.cc) must reproduce byte-for-byte the
Python implementations it replaces (ref parity targets:
src/yb/rocksdb/table/block_based_table_reader.cc:1144-1286 seek + bloom,
table/merger.cc:51 MergingIterator, docdb/doc_rowwise_iterator.cc RESOLVE).
Every test builds the same DB and compares the two paths directly.
"""

import os
import random

import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.doc_rowwise_iterator import DocRowwiseIterator
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.storage import native_read
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.utils import flags


pytestmark = pytest.mark.skipif(not native_read.available(),
                                reason="native read engine unavailable")


def _rand_value(rng) -> Value:
    r = rng.random()
    if r < 0.1:
        return Value.tombstone()
    if r < 0.15:
        return Value(is_object=True)
    if r < 0.3:
        return Value(primitive=rng.randrange(10**6),
                     ttl_ms=rng.choice([1, 10_000, 10**9]))
    return Value(primitive="v" * rng.randrange(1, 40))


def _build_db(tmp_path, seed=7, n_docs=120, n_batches=5) -> DB:
    """Multi-SST + live-memtable DB with versions, tombstones, TTLs,
    deep subdocuments, and bare-DocKey markers."""
    rng = random.Random(seed)
    db = DB(os.path.join(str(tmp_path), f"db{seed}"),
            DBOptions(device="native", auto_compact=False))
    t = 1000
    for batch in range(n_batches):
        items = []
        for _ in range(200):
            doc = rng.randrange(n_docs)
            dk = DocKey(range_components=(f"doc{doc:04d}",))
            kind = rng.random()
            if kind < 0.15:
                key = dk.encode()  # bare DocKey: init marker / row tombstone
                val = Value(is_object=True) if rng.random() < 0.6 \
                    else Value.tombstone()
            elif kind < 0.25:
                # deep subdocument path
                key = SubDocKey(dk, (("col", rng.randrange(4)),
                                     f"elem{rng.randrange(3)}")).encode(
                    include_ht=False)
                val = _rand_value(rng)
            else:
                key = SubDocKey(dk, (("col", rng.randrange(6)),)).encode(
                    include_ht=False)
                val = _rand_value(rng)
            t += rng.randrange(1, 3)
            items.append((key, DocHybridTime(HybridTime.from_micros(t),
                                             rng.randrange(3)),
                          val.encode()))
        db.write_batch(items, op_id=(1, batch + 1))
        if batch < n_batches - 1:
            db.flush()  # last batch stays in the memtable (overlay path)
    return db


def _python_iter(db, seek=b""):
    flags.set_flag("read_native", False)
    try:
        return list(db.iter_from(seek))
    finally:
        flags.set_flag("read_native", True)


class TestIterFromEquivalence:
    def test_full_stream_matches_python_merge(self, tmp_path):
        db = _build_db(tmp_path)
        native = list(db.iter_from(b""))
        oracle = _python_iter(db)
        assert native == oracle
        assert len(native) == 1000
        db.close()

    def test_seek_with_ht_suffix(self, tmp_path):
        db = _build_db(tmp_path, seed=8)
        oracle = _python_iter(db)
        # seek to every 97th oracle position, with its full internal key
        for i in range(0, len(oracle), 97):
            seek = oracle[i][0]
            assert list(db.iter_from(seek)) == oracle[i:], f"seek at {i}"
        db.close()

    def test_seek_prefix_only(self, tmp_path):
        db = _build_db(tmp_path, seed=9)
        oracle = _python_iter(db)
        dk = DocKey(range_components=("doc0050",)).encode()
        expect = [kv for kv in oracle if kv[0] >= dk]
        assert list(db.iter_from(dk)) == expect
        db.close()


class TestPointGetEquivalence:
    def test_random_gets_match_python(self, tmp_path):
        db = _build_db(tmp_path, seed=10)
        rng = random.Random(1)
        keys = []
        for doc in range(0, 120, 3):
            dk = DocKey(range_components=(f"doc{doc:04d}",))
            keys.append(dk.encode())
            for c in range(6):
                keys.append(SubDocKey(dk, (("col", c),)).encode(
                    include_ht=False))
        for key in keys:
            for read_ht in (None, HybridTime.from_micros(1500),
                            HybridTime.from_micros(
                                1000 + rng.randrange(2000))):
                got = db.get(key, read_ht)
                flags.set_flag("read_native", False)
                want = db.get(key, read_ht)
                flags.set_flag("read_native", True)
                assert got == want, (key, read_ht)
        db.close()

    def test_missing_keys(self, tmp_path):
        db = _build_db(tmp_path, seed=11)
        for doc in range(500, 540):
            key = DocKey(range_components=(f"doc{doc:04d}",)).encode()
            assert db.get(key) is None
        db.close()


class TestVisibleScanEquivalence:
    @pytest.mark.parametrize("read_us", [1100, 1700, 10**7])
    def test_visible_matches_resolve_visible(self, tmp_path, read_us):
        db = _build_db(tmp_path, seed=12)
        read_ht = HybridTime.from_micros(read_us)
        scan = db.scan_native(visible=True, read_ht_value=read_ht.value)
        assert scan is not None
        native = [(k, v, ht) for k, v, ht, _w, _f, _d in scan.entries()]
        flags.set_flag("read_native", False)
        try:
            from yugabyte_tpu.common.schema import Schema
            it = DocRowwiseIterator.__new__(DocRowwiseIterator)
            it._db = db
            it._read_ht = read_ht
            it._lower = b""
            it._upper = None
            it._entry_stream = None
            oracle = list(it._resolve_visible())
        finally:
            flags.set_flag("read_native", True)
        assert native == oracle
        db.close()

    def test_bounded_visible_scan(self, tmp_path):
        db = _build_db(tmp_path, seed=13)
        lower = DocKey(range_components=("doc0020",)).encode()
        upper = DocKey(range_components=("doc0060",)).encode()
        read_ht = HybridTime.from_micros(10**7)
        scan = db.scan_native(lower=lower, upper=upper, visible=True,
                              read_ht_value=read_ht.value)
        native = [(k, v, ht) for k, v, ht, _w, _f, _d in scan.entries()]
        flags.set_flag("read_native", False)
        try:
            it = DocRowwiseIterator.__new__(DocRowwiseIterator)
            it._db = db
            it._read_ht = read_ht
            it._lower = lower
            it._upper = upper
            it._entry_stream = None
            oracle = list(it._resolve_visible())
        finally:
            flags.set_flag("read_native", True)
        assert native == oracle
        db.close()


class TestCompressedBlocks:
    def test_zlib_blocks_served_natively(self, tmp_path):
        flags.set_flag("sst_compression", "zlib")
        try:
            db = _build_db(tmp_path, seed=14)
        finally:
            flags.set_flag("sst_compression", "none")
        native = list(db.iter_from(b""))
        oracle = _python_iter(db)
        assert native == oracle
        db.close()


class TestNativeFlushEquivalence:
    def test_native_flush_readback_matches_python_writer(self, tmp_path):
        # same content flushed through the native packed encoder and the
        # Python SSTWriter must produce identical merged streams
        dbs = []
        for sub, native_flush in (("n", True), ("p", False)):
            db = DB(os.path.join(str(tmp_path), sub),
                    DBOptions(device="native", auto_compact=False))
            rng = random.Random(21)
            items = []
            for i in range(500):
                dk = DocKey(range_components=(f"k{rng.randrange(100):03d}",))
                key = SubDocKey(dk, (("col", rng.randrange(4)),)).encode(
                    include_ht=False)
                items.append((key,
                              DocHybridTime(
                                  HybridTime.from_micros(5000 + i), 0),
                              _rand_value(rng).encode()))
            db.write_batch(items, op_id=(1, 1))
            if not native_flush:
                # force the slab/SSTWriter path by routing through a fake
                # device cache sentinel? simpler: call the python writer
                # via the public knob — temporarily mark engine unavailable
                from yugabyte_tpu.storage import native_engine
                saved = native_engine._available
                native_engine._available = False
                try:
                    db.flush()
                finally:
                    native_engine._available = saved
            else:
                db.flush()
            dbs.append(db)
        a = _python_iter(dbs[0])
        b = _python_iter(dbs[1])
        assert a == b
        # and the props agree on the doc-aware bits
        fa = dbs[0].versions.live_files()[0]
        fb = dbs[1].versions.live_files()[0]
        assert fa.props.n_entries == fb.props.n_entries
        assert fa.props.first_key == fb.props.first_key
        assert fa.props.last_key == fb.props.last_key
        assert fa.props.has_deep == fb.props.has_deep
        assert fa.props.max_expire_us == fb.props.max_expire_us
        for db in dbs:
            db.close()


class TestIngestPacked:
    def test_unsorted_ingest_readback(self, tmp_path):
        import numpy as np
        db = DB(os.path.join(str(tmp_path), "ing"),
                DBOptions(device="native", auto_compact=False))
        rng = random.Random(31)
        rows = []
        for i in range(2000):
            dk = DocKey(range_components=(f"u{rng.randrange(1000):04d}",))
            key = SubDocKey(dk, (("col", 1),)).encode(include_ht=False)
            rows.append((key, 7000 + i, Value(primitive=i).encode()))
        rng.shuffle(rows)  # ingest handles unsorted runs
        keys_blob = b"".join(r[0] for r in rows)
        koffs = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum([len(r[0]) for r in rows], out=koffs[1:])
        ht = np.array([HybridTime.from_micros(r[1]).value for r in rows],
                      dtype=np.uint64)
        wid = np.zeros(len(rows), dtype=np.uint32)
        vals_blob = b"".join(r[2] for r in rows)
        voffs = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum([len(r[2]) for r in rows], out=voffs[1:])
        fid = db.ingest_packed(keys_blob, koffs, ht, wid, vals_blob, voffs,
                               op_id=(1, 1))
        assert fid is not None
        stream = list(db.iter_from(b""))
        assert len(stream) == 2000
        assert stream == sorted(stream), "ingest must order unsorted input"
        # point-get the newest version of one doc
        probe = rows[0][0]
        got = db.get(probe)
        assert got is not None
        db.close()


class TestConcurrentChurn:
    def test_reads_stable_under_flush_and_compaction(self, tmp_path):
        """Writers + point readers + scanners race flushes and compactions:
        the native reader-set snapshots must never serve a torn view, hide
        a committed row, or crash on a freed handle (the refcount design
        replaces the reference's Version pinning, ref db/version_set.cc)."""
        import threading

        from yugabyte_tpu.docdb.value import Value

        db = DB(os.path.join(str(tmp_path), "churn"),
                DBOptions(device="native", auto_compact=True))
        n_keys = 400
        stop = threading.Event()
        errors = []
        write_floor = [0]  # generation fully written (all keys)

        def writer():
            gen = 0
            t = 10_000
            try:
                while not stop.is_set():
                    gen += 1
                    items = []
                    for i in range(n_keys):
                        dk = DocKey(range_components=(f"w{i:04d}",))
                        key = SubDocKey(dk, (("col", 0),)).encode(
                            include_ht=False)
                        t += 1
                        items.append((key, DocHybridTime(
                            HybridTime.from_micros(t), 0),
                            Value(primitive=gen).encode()))
                    db.write_batch(items, op_id=(1, gen))
                    write_floor[0] = gen
                    if gen % 3 == 0:
                        db.flush()
            except Exception as e:  # noqa: BLE001
                errors.append(("writer", repr(e)))

        def reader():
            import random
            rng = random.Random(5)
            try:
                while not stop.is_set():
                    floor = write_floor[0]
                    if floor == 0:
                        continue
                    i = rng.randrange(n_keys)
                    dk = DocKey(range_components=(f"w{i:04d}",))
                    key = SubDocKey(dk, (("col", 0),)).encode(
                        include_ht=False)
                    got = db.get(key)
                    assert got is not None, f"key w{i:04d} vanished"
                    v = Value.decode(got[1]).primitive
                    assert v >= floor, (
                        f"stale read: saw gen {v}, floor was {floor}")
            except Exception as e:  # noqa: BLE001
                errors.append(("reader", repr(e)))

        def scanner():
            try:
                while not stop.is_set():
                    floor = write_floor[0]
                    if floor == 0:
                        continue
                    seen = 0
                    for _ikey, _v in db.iter_from(b""):
                        seen += 1
                    assert seen >= n_keys, (
                        f"scan saw {seen} < {n_keys} entries")
            except Exception as e:  # noqa: BLE001
                errors.append(("scanner", repr(e)))

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (writer, reader, reader, scanner)]
        for t in threads:
            t.start()
        import time as _time
        _time.sleep(8)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        db.close()
        assert not errors, errors
