"""xCluster async replication: CDC producer + consumer pollers between two
live clusters (round-2 Missing #5; ref ent/src/yb/cdc/cdc_producer.cc,
ent/src/yb/tserver/cdc_poller.cc, twodc_output_client.cc)."""

import time

import pytest

from yugabyte_tpu.client.transaction import TransactionManager
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.cdc import poller as _poller  # registers xcluster flags
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags


def _schema():
    return Schema([ColumnSchema("k", DataType.STRING),
                   ColumnSchema("v", DataType.INT64)],
                  num_hash_key_columns=1, num_range_key_columns=0)


def _op(k, v):
    return QLWriteOp(WriteOpKind.INSERT, DocKey(hash_components=(k,)),
                     {"v": v})


@pytest.fixture(scope="module")
def clusters(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    flags.set_flag("xcluster_poll_interval_ms", 50)
    src = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("xc-src")))).start()
    dst = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("xc-dst")))).start()
    yield src, dst
    dst.shutdown()
    src.shutdown()


def _wait(pred, timeout_s=30.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


def test_xcluster_replicates_writes_deletes_and_txns(clusters):
    src, dst = clusters
    s_client, d_client = src.new_client(), dst.new_client()
    s_client.create_namespace("app")
    d_client.create_namespace("app")
    s_table = s_client.create_table("app", "orders", _schema(),
                                    num_tablets=2)
    d_table = d_client.create_table("app", "orders", _schema(),
                                    num_tablets=2)
    # deadline-poll leadership on both universes instead of racing the
    # fresh tablets' first election against the client retry budget
    # (the known tier-1 leadership-timing flake under CI load)
    src.wait_for_table_leaders("app", "orders")
    dst.wait_for_table_leaders("app", "orders")
    for i in range(20):
        s_client.write(s_table, [_op(f"o{i:03d}", i)])

    d_client.setup_universe_replication(
        "repl1", [src.masters[0].address],
        [["app", "orders", "app", "orders"]])

    def row_on_target(k):
        row = d_client.read_row(d_table, DocKey(hash_components=(k,)))
        return row.to_dict(d_table.schema) if row is not None else None

    # pre-existing rows arrive (stream starts from index 0)
    _wait(lambda: row_on_target("o013") is not None, msg="backlog row")
    assert row_on_target("o013")["v"] == 13
    # new writes stream continuously
    s_client.write(s_table, [_op("live1", 101)])
    _wait(lambda: row_on_target("live1") is not None, msg="live row")
    # source hybrid times are preserved (external HT application)
    s_row = s_client.read_row(s_table, DocKey(hash_components=("live1",)))
    d_row = d_client.read_row(d_table, DocKey(hash_components=("live1",)))
    assert s_row.write_ht.value == d_row.write_ht.value
    # deletes replicate as tombstones
    s_client.write(s_table, [QLWriteOp(WriteOpKind.DELETE_ROW,
                                       DocKey(hash_components=("o005",)))])
    _wait(lambda: row_on_target("o005") is None, msg="delete")
    # distributed transactions replicate atomically at the commit time
    mgr = TransactionManager(s_client)
    txn = mgr.begin()
    txn.write(s_table, [_op("t1", 1000)])
    txn.write(s_table, [_op("t2", 2000)])
    txn.commit()
    _wait(lambda: row_on_target("t1") is not None
          and row_on_target("t2") is not None, msg="txn rows")
    assert row_on_target("t1")["v"] == 1000
    assert row_on_target("t2")["v"] == 2000
    # checkpoints persist in the target master's sys catalog
    def checkpoint_advanced():
        metas = [m for t, _i, m in
                 dst.masters[0].catalog.sys.scan_all()
                 if t == "replication"]
        return metas and any(v > 0 for v in
                             metas[0].get("checkpoints", {}).values())
    _wait(checkpoint_advanced, msg="checkpoint persistence")
    s_client.close()
    d_client.close()


def test_xcluster_delete_replication_stops_stream(clusters):
    src, dst = clusters
    s_client, d_client = src.new_client(), dst.new_client()
    s_table = s_client.open_table("app", "orders")
    d_table = d_client.open_table("app", "orders")
    d_client.delete_universe_replication("repl1")
    time.sleep(0.5)  # heartbeat reconciles pollers away
    s_client.write(s_table, [_op("after-stop", 7)])
    time.sleep(1.0)
    row = d_client.read_row(d_table,
                            DocKey(hash_components=("after-stop",)))
    assert row is None
    s_client.close()
    d_client.close()
