"""Device-resident slab cache: SST key columns pinned in TPU HBM.

The TPU-native analog of the reference's block cache (ref:
rocksdb/util/lru_cache.cc) — but where the reference caches decoded blocks in
host RAM to avoid disk reads, this caches *staged key-column matrices* in
device HBM to avoid host->device transfers, which dominate compaction cost on
a transfer-limited interconnect. Flush and compaction write-through: every
new SST's key columns are staged once, so steady-state compaction finds all
inputs already resident and only ships back the (bit-packed) keep masks.

Values stay host-side: merge+GC only permutes and drops entries, so value
bytes never need to cross to the device at all (the original sidecar
insight, SURVEY.md section 2.7).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.ops.merge_gc import (
    _ROW_WORDS, StagedCols, bucket_size, build_sort_schedule,
    pad_template, stage_slab)
from yugabyte_tpu.ops.slabs import KVSlab

CacheKey = Tuple[str, int]  # (namespace, file_id) — file ids are per-DB


class DeviceSlabCache:
    """Server-wide cache; keys are namespaced per DB because VersionSet file
    ids are only unique within one DB (like the reference's per-DB file
    numbers under a shared block cache)."""

    def __init__(self, device=None, capacity_bytes: int = 4 << 30):
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        from yugabyte_tpu.utils import lock_rank
        self.device = device
        self.capacity = capacity_bytes
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "device_cache.slab_lock")
        self._map: "OrderedDict[CacheKey, StagedCols]" = \
            OrderedDict()                  # guarded-by: _lock
        self._used = 0                     # guarded-by: _lock
        # per-instance ints (tests diff fresh caches) + process-wide
        # registry counters so the hit ratio is scrapeable
        self.hits = 0                      # guarded-by: _lock
        self.misses = 0                    # guarded-by: _lock
        e = ROOT_REGISTRY.entity("server", "device_cache")
        self._c_hits = e.counter("device_cache_hits_total",
                                 "HBM slab cache hits")
        self._c_misses = e.counter("device_cache_misses_total",
                                   "HBM slab cache misses")
        self._g_used = e.gauge("device_cache_used_bytes",
                               "HBM bytes resident in the slab cache")

    def get(self, key: CacheKey) -> Optional[StagedCols]:
        with self._lock:
            staged = self._map.get(key)
            if staged is None:
                self.misses += 1
                self._c_misses.increment()
                return None
            self._map.move_to_end(key)
            self.hits += 1
            self._c_hits.increment()
            return staged

    def contains(self, key: CacheKey) -> bool:
        """Metrics-neutral probe (offload policy peeks without counting)."""
        with self._lock:
            return key in self._map

    def put(self, key: CacheKey, staged: StagedCols) -> None:
        with self._lock:
            prior = self._map.pop(key, None)
            if prior is not None:
                # replace, not refuse: a stale entry under a reused id must
                # never shadow fresh data (correctness, not just freshness)
                self._used -= prior.nbytes
            self._map[key] = staged
            self._used += staged.nbytes
            while self._used > self.capacity and len(self._map) > 1:
                _, old = self._map.popitem(last=False)
                self._used -= old.nbytes
            self._g_used.set(self._used)

    def drop(self, key: CacheKey) -> None:
        with self._lock:
            staged = self._map.pop(key, None)
            if staged is not None:
                self._used -= staged.nbytes

    def drop_namespace(self, namespace: str) -> None:
        """Evict everything a closed DB staged, freeing its HBM residency."""
        with self._lock:
            dead = [k for k in self._map if k[0] == namespace]
            for k in dead:
                self._used -= self._map.pop(k).nbytes

    def stage(self, key: CacheKey, slab: KVSlab) -> StagedCols:
        staged = stage_slab(slab, self.device)
        self.put(key, staged)
        return staged


class NamespacedSlabCache:
    """Per-DB view over a shared DeviceSlabCache: callers use bare file ids."""

    def __init__(self, shared: DeviceSlabCache, namespace: str):
        self._shared = shared
        self.namespace = namespace

    @property
    def device(self):
        return self._shared.device

    @property
    def hits(self):
        return self._shared.hits

    @property
    def misses(self):
        return self._shared.misses

    def get(self, file_id: int):
        return self._shared.get((self.namespace, file_id))

    def contains(self, file_id: int) -> bool:
        return self._shared.contains((self.namespace, file_id))

    def put(self, file_id: int, staged: StagedCols) -> None:
        self._shared.put((self.namespace, file_id), staged)

    def drop(self, file_id: int) -> None:
        self._shared.drop((self.namespace, file_id))

    def drop_all(self) -> None:
        self._shared.drop_namespace(self.namespace)

    def stage(self, file_id: int, slab: KVSlab) -> StagedCols:
        return self._shared.stage((self.namespace, file_id), slab)


class HostStagingPool:
    """Reusable host-side staging arrays for stage A of the compaction
    pipeline (ops/run_merge.stage_runs_from_slabs packs column matrices
    into these before the H2D upload).

    Shape buckets make reuse effective: every chunk of a pipelined job
    (and most jobs of a tablet's lifetime) stages the same [r, k_pad*m]
    matrix shape, so after warmup the host never allocates — the pinned
    pages stay hot and the allocator never fragments under a double-
    buffered producer that holds two staging arrays in flight.

    Callers must only release() an array once the upload has COPIED it
    (true on tpu/gpu backends; the CPU backend may alias host memory, so
    its callers skip release and the array is simply garbage-collected).
    """

    def __init__(self, max_per_shape: int = 2, max_bytes: int = 1 << 30):
        from yugabyte_tpu.utils import lock_rank
        self._free: dict = {}              # guarded-by: _lock
        self._bytes = 0                    # guarded-by: _lock
        # ids of arrays acquired and not yet released/forgotten — the
        # chaos harness's leak detector: after every job (including a
        # cancelled or device-faulted one) this must drain back to 0
        self._leases: set = set()          # guarded-by: _lock
        self._max_per_shape = max_per_shape
        self._max_bytes = max_bytes
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "device_cache.staging_pool_lock")
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        e = ROOT_REGISTRY.entity("server", "device_cache")
        self._c_reuse = e.counter(
            "staging_pool_reuse_total",
            "stage-A packings served from a pooled host array")
        self._c_alloc = e.counter(
            "staging_pool_alloc_total",
            "stage-A packings that allocated a fresh host array")
        self._g_leases = e.gauge(
            "staging_pool_outstanding_lease_count",
            "staging arrays acquired and not yet released")

    def acquire(self, shape: Tuple[int, int], dtype=np.uint32) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            bucket = self._free.get(key)
            if bucket:
                arr = bucket.pop()
                self._bytes -= arr.nbytes
                self._leases.add(id(arr))
                self._g_leases.set(len(self._leases))
                self._c_reuse.increment()
                return arr
        arr = np.empty(shape, dtype=dtype)
        with self._lock:
            self._leases.add(id(arr))
            self._g_leases.set(len(self._leases))
        self._c_alloc.increment()
        return arr

    def release(self, arr: np.ndarray) -> None:
        key = (arr.shape, arr.dtype.str)
        with self._lock:
            self._leases.discard(id(arr))
            self._g_leases.set(len(self._leases))
            bucket = self._free.setdefault(key, [])
            if (len(bucket) < self._max_per_shape
                    and self._bytes + arr.nbytes <= self._max_bytes):
                bucket.append(arr)
                self._bytes += arr.nbytes

    def forget(self, arr: np.ndarray) -> None:
        """End a lease WITHOUT recycling the pages: the CPU backend may
        alias the array's memory into the device buffer, so the caller
        hands the array off for garbage collection instead of release().
        Not a leak — the lease is accounted done."""
        with self._lock:
            self._leases.discard(id(arr))
            self._g_leases.set(len(self._leases))

    def outstanding(self) -> int:
        """Leases neither released nor forgotten — the chaos soak asserts
        this returns to zero after fault windows heal."""
        with self._lock:
            return len(self._leases)


_staging_pool: Optional[HostStagingPool] = None  # guarded-by: _staging_pool_lock
_staging_pool_lock = threading.Lock()


def host_staging_pool() -> HostStagingPool:
    """Process-wide staging pool (one per process, like the slab cache)."""
    global _staging_pool
    with _staging_pool_lock:
        if _staging_pool is None:
            _staging_pool = HostStagingPool()
        return _staging_pool


def concat_staged(staged_list: Sequence[StagedCols]) -> StagedCols:
    """Concatenate staged inputs ON DEVICE into one padded cols matrix.

    All transfers avoided: pad each input's width to the max, concatenate
    along entries, pad entry count to the bucket size — all jnp ops on the
    cached arrays' device (placement follows the cache's device).
    """
    import jax.numpy as jnp

    w = max(s.w for s in staged_list)
    n = sum(s.n for s in staged_list)
    n_pad = bucket_size(n)
    parts = []
    for s in staged_list:
        cols = s.cols_dev[:, :s.n]  # strip per-input padding
        if s.w < w:
            pad_words = jnp.zeros((w - s.w, s.n), dtype=jnp.uint32)
            cols = jnp.concatenate([cols, pad_words], axis=0)
        parts.append(cols)
    cat = jnp.concatenate(parts, axis=1)
    tail = n_pad - n
    if tail:
        pad = jnp.asarray(pad_template(cat.shape[0]))[:, None]
        cat = jnp.concatenate([cat, jnp.tile(pad, (1, tail))], axis=1)
    # Merged schedule: a column is skippable only if CONSTANT WITH THE SAME
    # VALUE across every input (constant-per-input with differing values
    # still orders the merge). Inputs narrower than w expose the extra word
    # rows as constant zero.
    r_total = _ROW_WORDS + w
    is_const = np.ones(r_total, bool)
    first_vals: List[Optional[int]] = [None] * r_total
    for s in staged_list:
        for row in range(r_total):
            if row >= _ROW_WORDS + s.w:
                c, v = True, 0  # implicit zero-pad word rows
            else:
                c = bool(s.col_const[row]) if s.col_const is not None else False
                v = int(s.col_first[row]) if s.col_first is not None else 0
            if not c:
                is_const[row] = False
            elif first_vals[row] is None:
                first_vals[row] = v
            elif first_vals[row] != v:
                is_const[row] = False
    sort_rows, n_sort = build_sort_schedule(w, is_const)
    return StagedCols(cat, sort_rows, n_sort, n, n_pad, w)
