"""Embedded status/metrics webserver.

Capability parity with the reference (ref: src/yb/server/webserver.cc +
per-server path handlers master-path-handlers.cc / tserver-path-handlers.cc;
metric endpoints util/metrics.h:449-518 — JSON `/metrics` and Prometheus
`/prometheus-metrics`). Handlers are plain callables returning
(content_type, body); every server registers its own status pages.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Tuple

from yugabyte_tpu.utils.metrics import MetricRegistry

Handler = Callable[[], Tuple[str, str]]


class Webserver:
    def __init__(self, metrics: MetricRegistry,
                 bind_host: str = "127.0.0.1", port: int = 0):
        self._metrics = metrics
        self._handlers: Dict[str, Handler] = {}
        outer = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
                pass

            def do_GET(self):  # noqa: N802 — stdlib name
                path = self.path.split("?", 1)[0]
                try:
                    ctype, body = outer._dispatch(path)
                    code = 200
                except KeyError:
                    ctype, body = "text/plain", f"no handler for {path}\n"
                    code = 404
                except Exception as e:  # noqa: BLE001 — surface as 500
                    ctype, body = "text/plain", f"error: {e}\n"
                    code = 500
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((bind_host, port), _Req)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="webserver")
        self._thread.start()
        self.register("/healthz", lambda: ("text/plain", "ok\n"))
        self.register("/metrics", self._json_metrics)
        self.register("/prometheus-metrics", self._prom_metrics)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def register_json(self, path: str, provider: Callable[[], object]) -> None:
        self._handlers[path] = lambda: (
            "application/json", json.dumps(provider(), indent=2,
                                           default=str) + "\n")

    def _dispatch(self, path: str) -> Tuple[str, str]:
        return self._handlers[path]()

    def _json_metrics(self) -> Tuple[str, str]:
        return "application/json", self._metrics.to_json()

    def _prom_metrics(self) -> Tuple[str, str]:
        return "text/plain; version=0.0.4", self._metrics.to_prometheus()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
