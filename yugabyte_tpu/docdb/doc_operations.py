"""QL-style document write operations -> flattened DocDB KV pairs.

Capability parity with the reference's write-op application (ref:
src/yb/docdb/ql_operation.cc / pgsql_operation.cc:366 `PgsqlWriteOperation::
Apply`, docdb/doc_write_batch): a row INSERT writes a *liveness* system
column plus one KV per non-null value column; UPDATE writes only the touched
columns; row DELETE writes a tombstone at the bare DocKey which shadows every
older column write (ref: docdb semantics in docdb/doc.md).

Lock determination follows DetermineKeysToLock (ref: src/yb/docdb/docdb.cc):
strong intent on each written doc path, weak intents on its prefixes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

from yugabyte_tpu.common.schema import Schema
from yugabyte_tpu.docdb.doc_key import (DocKey, PrimitiveType,
                                        PrimitiveValue, SubDocKey)
from yugabyte_tpu.docdb.lock_manager import (
    IntentType, LockBatch, doc_path_lock_entries)
from yugabyte_tpu.docdb.value import Value


@lru_cache(maxsize=8192)
def column_key_suffix(cid: int) -> bytes:
    """Encoded column-id subkey (what SubDocKey appends after the doc
    key). Column ids repeat across every row of a table, so the batched
    encode path concatenates ``doc_key.encode() + column_key_suffix(cid)``
    — byte-identical to SubDocKey(dk, (("col", cid),)).encode(
    include_ht=False) without re-encoding (and re-hashing) the doc key
    once per column."""
    buf = bytearray()
    PrimitiveValue.encode_column_id(cid, buf)
    return bytes(buf)

# System column marking row liveness (ref: common/ql_value / SystemColumnIds::
# kLivenessColumn). Encoded with kSystemColumnId, so it sorts before all
# regular (kColumnId) columns of the row.
kLivenessColumnId = -1


class WriteOpKind(enum.Enum):
    INSERT = "insert"    # upsert full row + liveness marker
    UPDATE = "update"    # touched columns only, no liveness
    DELETE_ROW = "delete_row"
    DELETE_COLS = "delete_cols"


@dataclass
class QLWriteOp:
    """One row-level write. `values` maps value-column name -> primitive;
    a None value in an UPDATE means "delete this column" (CQL SET c = null)."""

    kind: WriteOpKind
    doc_key: DocKey
    values: Dict[str, PrimitiveType] = field(default_factory=dict)
    ttl_ms: Optional[int] = None
    columns_to_delete: Tuple[str, ...] = ()
    # YCQL collection ops per column, applied IN ORDER (storage rides
    # subdocuments — docdb/subdocument.py; ref doc_write_batch.cc
    # InsertSubDocument / ExtendSubDocument):
    #   ("replace", {k: v})  SET m = {...}  — init marker + entries
    #   ("merge",   {k: v})  SET m = m + {...} / m['k'] = v — no marker
    #   ("del_keys", [k..])  DELETE m['k'] / SET m = m - {...}
    # Value: a LIST of such ops per column (one UPDATE may mix element
    # writes and element deletes on the same column).
    collection_ops: Dict[str, List[Tuple[str, object]]] = field(
        default_factory=dict)
    # Index backfill only (ref: tablet.cc:2088 BackfillIndexes writing at
    # the backfill read time): entries are stamped with THIS hybrid time
    # instead of the op's, so concurrent index maintenance — which writes at
    # now() — always supersedes backfilled entries.
    backfill_ht: Optional[int] = None

    # ------------------------------------------------------------- KV pairs
    def to_kv_pairs(self, schema: Schema) -> List[Tuple[bytes, bytes]]:
        """Flattened (subdoc_key_without_ht, encoded_value) pairs, in the
        order they receive intra-batch write ids."""
        dk = self.doc_key
        # Encode the doc key ONCE per op (it includes the partition-hash
        # computation); every column key is a pure byte concat from it.
        # Byte-identical to the per-column SubDocKey encode — the batched
        # write path leans on this (one hash + one component encode per
        # ROW, not per KV).
        dk_enc = dk.encode()
        out: List[Tuple[bytes, bytes]] = []

        def col_key(cid: int) -> bytes:
            return dk_enc + column_key_suffix(cid)

        if self.kind == WriteOpKind.DELETE_ROW:
            out.append((dk_enc, Value.tombstone().encode()))
            return out
        if self.kind == WriteOpKind.DELETE_COLS:
            for name in self.columns_to_delete:
                out.append((col_key(schema.column_id(name)),
                            Value.tombstone().encode()))
            self._collection_kv_pairs(schema, out)
            return out
        if self.kind == WriteOpKind.INSERT:
            out.append((col_key(kLivenessColumnId),
                        Value(primitive=None, ttl_ms=self.ttl_ms).encode()))
        for name, v in self.values.items():
            cid = schema.column_id(name)
            if v is None and self.kind == WriteOpKind.UPDATE:
                out.append((col_key(cid), Value.tombstone().encode()))
            else:
                out.append((col_key(cid),
                            Value(primitive=v, ttl_ms=self.ttl_ms).encode()))
        self._collection_kv_pairs(schema, out)
        return out

    def _collection_kv_pairs(self, schema: Schema,
                             out: List[Tuple[bytes, bytes]]) -> None:
        dk = self.doc_key
        for name, ops in self.collection_ops.items():
            cid = schema.column_id(name)
            from yugabyte_tpu.docdb.subdocument import subdocument_writes
            path = (("col", cid),)
            for op, payload in ops:
                if op == "replace":
                    out.extend(subdocument_writes(dk, path, dict(payload),
                                                  ttl_ms=self.ttl_ms))
                elif op == "merge":
                    # element writes WITHOUT the init marker: older
                    # entries at other keys survive (ExtendSubDocument)
                    for k, v in dict(payload).items():
                        out.extend(subdocument_writes(dk, path + (k,), v,
                                                      ttl_ms=self.ttl_ms))
                elif op == "del_keys":
                    for k in payload:
                        out.append((SubDocKey(dk, path + (k,)).encode(
                            include_ht=False), Value.tombstone().encode()))
                else:
                    raise ValueError(f"unknown collection op {op!r}")

    # ---------------------------------------------------------------- locks
    def lock_entries(self, schema: Schema,
                     kv_pairs: Optional[List[Tuple[bytes, bytes]]] = None
                     ) -> List[Tuple[bytes, IntentType]]:
        dk_encoded = self.doc_key.encode()
        if kv_pairs is None:
            kv_pairs = self.to_kv_pairs(schema)
        entries: List[Tuple[bytes, IntentType]] = []
        for full_key, _v in kv_pairs:
            prefixes = [dk_encoded] if full_key != dk_encoded else []
            entries.extend(doc_path_lock_entries(full_key, prefixes, is_write=True))
        return entries


def prepare_and_assemble(ops: Sequence[QLWriteOp], schema: Schema,
                         lock_manager, timeout_s: float = 10.0
                         ) -> Tuple[LockBatch, List[Tuple[bytes, bytes]]]:
    """Encode each op ONCE; derive both the lock batch and the flattened
    write batch from the same KV pairs (ref: docdb.h:109
    PrepareDocWriteOperation + :127 AssembleDocWriteBatch). The index in the
    returned list becomes the intra-batch write_id."""
    entries: List[Tuple[bytes, IntentType]] = []
    all_pairs: List[Tuple[bytes, bytes]] = []
    for op in ops:
        pairs = op.to_kv_pairs(schema)
        entries.extend(op.lock_entries(schema, pairs))
        if op.backfill_ht:
            all_pairs.extend((k, v, op.backfill_ht) for k, v in pairs)
        else:
            all_pairs.extend(pairs)
    batch = lock_manager.lock(LockBatch(entries), timeout_s=timeout_s)
    return batch, all_pairs
