"""lock-discipline: `# guarded-by: <lock>` annotations are enforced.

Convention (adopted across the threaded modules — storage/compaction.py,
storage/device_cache.py, tserver/maintenance_manager.py,
consensus/log.py, consensus/raft.py, rpc/):

- Declare a shared attribute's lock on its initializing assignment:

      self._map = OrderedDict()        # guarded-by: _lock
      _staging_pool = None             # guarded-by: _staging_pool_lock

  (instance attributes in a class body; bare names at module level).

- Every later read or write of an annotated name must happen lexically
  inside `with self.<lock>:` (or `with <lock>:` for module globals) —
  or inside a function that declares the caller holds it:

      def _advance_commit_unlocked(self):          # convention, or
      def _gcable_segments(self):  # guarded-by: _cv

  The `*_unlocked`/`*_locked` name suffix is the repo's (and the
  reference's) caller-holds convention and is honored as such.

- `threading.Condition(self._lock)` makes the condition an alias of the
  lock: holding either satisfies a guard declared as either. Explicit
  aliasing: `# lock-alias: <name>` on the assignment.

__init__/__del__ bodies are exempt (pre-publication / teardown).
Waive a deliberate unguarded access (e.g. a benign racy fast-path read
whose publication happens under the lock) with
`# yblint: disable=lock-discipline` plus a justifying comment.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.analysis.core import AnalysisPass, FileContext, Finding

PASS_NAME = "lock-discipline"

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w]*)")
_ALIAS_RE = re.compile(r"#\s*lock-alias:\s*([A-Za-z_][\w]*)")
_EXEMPT_METHODS = {"__init__", "__del__", "__post_init__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Scope:
    """Guard tables for one class (or the module itself)."""

    def __init__(self) -> None:
        self.guards: Dict[str, str] = {}        # attr -> lock name
        self.aliases: Dict[str, Set[str]] = {}  # lock -> equivalence set

    def alias(self, a: str, b: str) -> None:
        group = (self.aliases.get(a, {a}) | self.aliases.get(b, {b}))
        for name in group:
            self.aliases[name] = group

    def satisfied_by(self, guard: str, held: Set[str]) -> bool:
        group = self.aliases.get(guard, {guard})
        return bool(group & held)


class LockDisciplinePass(AnalysisPass):
    name = PASS_NAME

    def run(self, ctx: FileContext) -> List[Finding]:
        class_scopes: Dict[ast.ClassDef, _Scope] = {}
        module_scope = _Scope()
        self._collect(ctx, class_scopes, module_scope)
        if not module_scope.guards and \
                not any(s.guards for s in class_scopes.values()):
            return []
        findings: List[Finding] = []
        for cls, scope in class_scopes.items():
            if scope.guards:
                findings.extend(self._check_class(ctx, cls, scope))
        if module_scope.guards:
            findings.extend(self._check_module(ctx, module_scope))
        return findings

    # --------------------------------------------------------- collection
    def _collect(self, ctx: FileContext,
                 class_scopes: Dict[ast.ClassDef, _Scope],
                 module_scope: _Scope) -> None:
        for node in ctx.nodes_of(ast.Assign, ast.AnnAssign):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            # the annotation comment may sit on any physical line of a
            # multi-line assignment (backslash/paren continuations)
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            text = "\n".join(ctx.line_text(ln)
                             for ln in range(node.lineno, end + 1))
            m_guard = _GUARDED_RE.search(text)
            m_alias = _ALIAS_RE.search(text)
            owner = self._owning_class(ctx, node)
            scope = class_scopes.setdefault(owner, _Scope()) \
                if owner is not None else module_scope
            for t in targets:
                attr = _self_attr(t)
                name = attr if attr is not None else (
                    t.id if isinstance(t, ast.Name) else None)
                if name is None:
                    continue
                if m_guard:
                    scope.guards[name] = m_guard.group(1)
                if m_alias:
                    scope.alias(name, m_alias.group(1))
                # auto-alias: self._cv = threading.Condition(self._lock)
                v = node.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr == "Condition" and v.args):
                    inner = _self_attr(v.args[0]) or (
                        v.args[0].id if isinstance(v.args[0], ast.Name)
                        else None)
                    if inner:
                        scope.alias(name, inner)

    def _owning_class(self, ctx: FileContext,
                      node: ast.AST) -> Optional[ast.ClassDef]:
        for a in ctx.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

    # ------------------------------------------------------------- checks
    def _held_locks(self, ctx: FileContext, node: ast.AST,
                    fn: ast.AST, self_attrs: bool) -> Set[str]:
        """Lock names whose `with` blocks lexically enclose `node`
        (stopping at the function boundary), plus caller-holds
        declarations on the function itself."""
        held: Set[str] = set()
        for a in ctx.ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    e = item.context_expr
                    name = _self_attr(e) if self_attrs else None
                    if name is None and isinstance(e, ast.Name):
                        name = e.id
                    if name is None and isinstance(e, ast.Attribute):
                        name = e.attr  # e.g. with self._shared._lock
                    if name:
                        held.add(name)
            if a is fn:
                break
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if fn.name.endswith(("_unlocked", "_locked")):
                held.add("*")  # caller-holds convention: satisfies any
            m = _GUARDED_RE.search(ctx.line_text(fn.lineno))
            if m:
                held.add(m.group(1))
        return held

    def _check_access(self, ctx: FileContext, scope: _Scope, name: str,
                      node: ast.AST, fn: ast.AST,
                      self_attrs: bool) -> Optional[Finding]:
        guard = scope.guards[name]
        held = self._held_locks(ctx, node, fn, self_attrs)
        if "*" in held or scope.satisfied_by(guard, held):
            return None
        is_store = isinstance(getattr(node, "ctx", None),
                              (ast.Store, ast.Del))
        kind = "write" if is_store else "read"
        return ctx.finding(
            self.name, "unguarded-access", node,
            f"{kind} of {name!r} (guarded-by: {guard}) outside "
            f"`with {'self.' if self_attrs else ''}{guard}:`")

    def _direct_body(self, fn: ast.AST) -> List[ast.AST]:
        """Nodes of fn excluding nested def bodies (each def is analyzed
        once, with its own held-lock context — an enclosing `with` does
        not guard a nested function's later execution)."""
        out: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     scope: _Scope) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in _EXEMPT_METHODS or self._inside_exempt(ctx, fn):
                continue
            for node in self._direct_body(fn):
                attr = _self_attr(node)
                if attr is None or attr not in scope.guards:
                    continue
                f = self._check_access(ctx, scope, attr, node, fn, True)
                if f is not None:
                    out.append(f)
        return out

    def _check_module(self, ctx: FileContext,
                      scope: _Scope) -> List[Finding]:
        out: List[Finding] = []
        for fn in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            if fn.name in _EXEMPT_METHODS or self._inside_exempt(ctx, fn):
                continue
            for node in self._direct_body(fn):
                if not isinstance(node, ast.Name) \
                        or node.id not in scope.guards:
                    continue
                # only flag accesses to the module global, not shadowing
                # locals/params of the same name
                if self._is_local(fn, node.id):
                    continue
                f = self._check_access(ctx, scope, node.id, node, fn, False)
                if f is not None:
                    out.append(f)
        return out

    def _inside_exempt(self, ctx: FileContext, fn: ast.AST) -> bool:
        """Nested defs inside __init__ et al share the exemption (e.g.
        callbacks constructed pre-publication)."""
        return any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and a.name in _EXEMPT_METHODS
                   for a in ctx.ancestors(fn))

    def _is_local(self, fn: ast.AST, name: str) -> bool:
        """Name is a parameter of fn (assigned names declared `global`
        still refer to the module binding)."""
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        if name in params:
            return True
        declared_global = any(
            isinstance(n, ast.Global) and name in n.names
            for n in ast.walk(fn))
        if declared_global:
            return False
        # assigned somewhere in fn without `global` -> it's a local
        for n in ast.walk(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                              ast.NamedExpr, ast.For)):
                targets = getattr(n, "targets", None) or \
                    [getattr(n, "target", None)]
                for t in targets:
                    if t is None:
                        continue
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name) and leaf.id == name:
                            return True
        return False
