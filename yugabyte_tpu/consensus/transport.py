"""Consensus transport seam.

The reference sends consensus traffic through its custom RPC framework
(ref: src/yb/consensus/consensus_peers.h:131 `Peer::SendNextRequest` over a
`PeerProxy`). Here the seam is `PeerProxyIf` with two calls — UpdateConsensus
(AppendEntries) and RequestVote — so the same RaftConsensus runs over:

- `LocalTransport`: in-process dispatch between peers in one interpreter
  (the MiniCluster path, ref rpc/local_call.h bypass), with fault injection
  for failure tests, and
- the host RPC layer (yugabyte_tpu/rpc) for real multi-process clusters.

Fault semantics are shared with the RPC layer: LocalTransport delegates
to the same `NemesisRules` engine (rpc/nemesis.py) the messenger
consults, so chaos tests express symmetric/one-way partitions, drops,
latency and duplicate delivery identically over both fabrics.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from yugabyte_tpu.rpc.nemesis import (LinkBlocked, LinkDropped, LinkRule,
                                      NemesisRules)


class PeerUnreachable(Exception):
    pass


class LocalTransport:
    """In-process message fabric between named consensus instances."""

    def __init__(self, seed: int = 0):
        from yugabyte_tpu.utils import lock_rank
        self._peers: Dict[str, object] = {}        # guarded-by: _lock
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "local_transport._lock")
        # the shared fault-rule engine (same semantics as the messenger's
        # nemesis hook); the drop-probability convenience keeps a handle
        # to its rule so re-setting replaces instead of stacking
        self.rules = NemesisRules(seed=seed)
        self._drop_rule: Optional[LinkRule] = None  # guarded-by: _lock

    def register(self, peer_id: str, consensus: object) -> None:
        with self._lock:
            self._peers[peer_id] = consensus

    def unregister(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(peer_id, None)

    # ------------------------------------------------------ fault injection
    def _known(self, name: str) -> bool:  # guarded-by: _lock
        return name in self._peers or \
            any(p.startswith(name + "/") for p in self._peers)

    def _require_known(self, what: str, *names: str) -> None:
        with self._lock:
            # a silent no-op fault (name not matching any registered peer
            # id) makes fault tests pass vacuously — fail loudly
            for name in names:
                if self._peers and not self._known(name):
                    raise ValueError(
                        f"{what}({name!r}): no such peer; registered: "
                        f"{sorted(self._peers)}")

    def partition(self, a: str, b: str, one_way: bool = False) -> None:
        """Cut the a<->b link (or only a->b when one_way): faults match
        the full consensus id ("ts0/t1") OR the server part ("ts0") — a
        network partition cuts SERVERS, so tests express it per-server
        and it applies to every tablet channel between them."""
        self._require_known("partition", a, b)
        self.rules.partition(a, b, one_way=one_way)

    def isolate(self, peer_id: str) -> None:
        """Cut peer_id off from everyone (crash-failure emulation)."""
        self._require_known("isolate", peer_id)
        self.rules.isolate(peer_id)

    def heal(self) -> None:
        with self._lock:
            self._drop_rule = None
        self.rules.heal()

    def set_drop_probability(self, p: float) -> None:
        """Drop every link's requests with probability p (0 clears)."""
        with self._lock:
            old = self._drop_rule
            self._drop_rule = None
        if old is not None:
            self.rules.remove_rule(old)
        if p > 0:
            rule = self.rules.add_rule(LinkRule("*", "*", drop_prob=p))
            with self._lock:
                self._drop_rule = rule

    def set_latency(self, src: str, dst: str, delay_s: float,
                    jitter_s: float = 0.0) -> None:
        self._require_known("set_latency", src, dst)
        self.rules.latency(src, dst, delay_s, jitter_s=jitter_s)

    def set_duplicate_probability(self, src: str, dst: str,
                                  p: float) -> None:
        self._require_known("set_duplicate", src, dst)
        self.rules.duplicate(src, dst, p)

    def _check_link(self, src: str, dst: str) -> Tuple[object, object]:
        try:
            verdict = self.rules.check_link(src, dst)
        except (LinkBlocked, LinkDropped) as e:
            raise PeerUnreachable(f"{src}->{dst}: {e}") from e
        with self._lock:
            peer = self._peers.get(dst)
        if peer is None:
            raise PeerUnreachable(f"{src}->{dst}: unknown peer")
        return peer, verdict

    # ------------------------------------------------------------ dispatch
    def update_consensus(self, src: str, dst: str, request):
        peer, verdict = self._check_link(src, dst)
        ctx = getattr(request, "trace_ctx", None)
        if ctx is not None:
            # mirror the RPC path's inbound adoption: the in-process hop
            # still produces a per-peer handler span under the same
            # trace_id, so LocalTransport clusters trace like real ones
            from yugabyte_tpu.utils.trace import Trace
            with Trace.from_wire_context(ctx, f"consensus.update:{dst}"):
                resp = peer.handle_update(request)
        else:
            resp = peer.handle_update(request)
        if verdict.duplicate:
            peer.handle_update(request)  # second delivery; resp discarded
        if verdict.drop_response:
            raise PeerUnreachable(f"{src}->{dst}: response dropped "
                                  "(nemesis)")
        return resp

    def request_vote(self, src: str, dst: str, request):
        peer, verdict = self._check_link(src, dst)
        resp = peer.handle_vote_request(request)
        if verdict.duplicate:
            peer.handle_vote_request(request)
        if verdict.drop_response:
            raise PeerUnreachable(f"{src}->{dst}: response dropped "
                                  "(nemesis)")
        return resp
