"""Minimal PostgreSQL v3 wire-protocol client, used by tests to prove the
YSQL server speaks the real protocol (startup handshake, simple query,
RowDescription/DataRow parsing, ErrorResponse, ReadyForQuery status)."""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Tuple


class PgWireError(Exception):
    def __init__(self, sqlstate: str, message: str):
        super().__init__(f"{sqlstate}: {message}")
        self.sqlstate = sqlstate
        self.message = message


class QueryResult:
    def __init__(self):
        self.columns: Optional[List[Tuple[str, int]]] = None
        self.rows: List[List[Optional[str]]] = []
        self.tag: Optional[str] = None


class PgWireClient:
    def __init__(self, host: str, port: int, database: str = "postgres",
                 user: str = "tester", try_ssl: bool = False):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.params = {}
        self.txn_status = None
        if try_ssl:
            self.sock.sendall(struct.pack(">II", 8, 80877103))
            assert self._recv_exact(1) == b"N", "expected SSL refusal"
        body = struct.pack(">I", 196608)
        for k, v in (("user", user), ("database", database)):
            body += k.encode() + b"\x00" + v.encode() + b"\x00"
        body += b"\x00"
        self.sock.sendall(struct.pack(">I", len(body) + 4) + body)
        # consume until ReadyForQuery
        while True:
            t, payload = self._recv_msg()
            if t == b"R":
                (code,) = struct.unpack_from(">I", payload, 0)
                assert code == 0, f"unexpected auth code {code}"
            elif t == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            elif t == b"K":
                pass
            elif t == b"Z":
                self.txn_status = payload.decode()
                return
            elif t == b"E":
                raise PgWireError(*self._parse_error(payload))
            else:
                raise AssertionError(f"unexpected startup message {t!r}")

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("server closed connection")
            buf += chunk
        return buf

    def _recv_msg(self):
        t = self._recv_exact(1)
        (length,) = struct.unpack(">I", self._recv_exact(4))
        return t, self._recv_exact(length - 4)

    @staticmethod
    def _parse_error(payload: bytes):
        fields = {}
        pos = 0
        while pos < len(payload) and payload[pos] != 0:
            code = chr(payload[pos])
            end = payload.index(b"\x00", pos + 1)
            fields[code] = payload[pos + 1: end].decode()
            pos = end + 1
        return fields.get("C", "?????"), fields.get("M", "")

    def query(self, sql: str) -> List[QueryResult]:
        """Simple-query protocol: returns one QueryResult per statement.
        Raises PgWireError on ErrorResponse (after draining to ready)."""
        self.sock.sendall(b"Q" + struct.pack(">I", len(sql.encode()) + 5)
                          + sql.encode() + b"\x00")
        results = []
        cur = QueryResult()
        error = None
        while True:
            t, payload = self._recv_msg()
            if t == b"T":
                cur.columns = []
                (n,) = struct.unpack_from(">H", payload, 0)
                pos = 2
                for _ in range(n):
                    end = payload.index(b"\x00", pos)
                    name = payload[pos:end].decode()
                    (oid,) = struct.unpack_from(">I", payload, end + 7)
                    cur.columns.append((name, oid))
                    pos = end + 19
            elif t == b"D":
                (n,) = struct.unpack_from(">H", payload, 0)
                pos = 2
                row: List[Optional[str]] = []
                for _ in range(n):
                    (ln,) = struct.unpack_from(">i", payload, pos)
                    pos += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[pos:pos + ln].decode())
                        pos += ln
                cur.rows.append(row)
            elif t == b"C":
                cur.tag = payload[:-1].decode()
                results.append(cur)
                cur = QueryResult()
            elif t == b"I":
                results.append(cur)
                cur = QueryResult()
            elif t == b"E":
                error = PgWireError(*self._parse_error(payload))
            elif t == b"Z":
                self.txn_status = payload.decode()
                if error is not None:
                    raise error
                return results
            else:
                raise AssertionError(f"unexpected message {t!r}")

    # --------------------------------------------- extended query protocol
    def _send_msg(self, t: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(t + struct.pack(">I", len(payload) + 4) + payload)

    def parse(self, name: str, sql: str,
              param_oids: Optional[List[int]] = None) -> None:
        oids = param_oids or []
        payload = (name.encode() + b"\x00" + sql.encode() + b"\x00"
                   + struct.pack(">H", len(oids))
                   + b"".join(struct.pack(">i", o) for o in oids))
        self._send_msg(b"P", payload)

    def bind(self, portal: str, stmt: str,
             params: Optional[List[Optional[str]]] = None) -> None:
        """Text-format parameters, like libpq's default."""
        params = params or []
        payload = [portal.encode() + b"\x00" + stmt.encode() + b"\x00",
                   struct.pack(">H", 0),                # all-text formats
                   struct.pack(">H", len(params))]
        for p in params:
            if p is None:
                payload.append(struct.pack(">i", -1))
            else:
                b = str(p).encode()
                payload.append(struct.pack(">i", len(b)) + b)
        payload.append(struct.pack(">H", 0))            # result formats
        self._send_msg(b"B", b"".join(payload))

    def describe(self, kind: str, name: str) -> None:
        self._send_msg(b"D", kind.encode() + name.encode() + b"\x00")

    def execute_portal(self, portal: str, max_rows: int = 0) -> None:
        self._send_msg(b"E", portal.encode() + b"\x00"
                       + struct.pack(">i", max_rows))

    def sync(self) -> None:
        self._send_msg(b"S")

    def fetch_paged(self, sql: str,
                    params: Optional[List[Optional[str]]] = None,
                    max_rows: int = 10):
        """Portal-suspension paging: Parse/Bind once, then repeated
        Execute(max_rows) until CommandComplete.  Returns (rows, executes,
        tag)."""
        self.parse("", sql)
        self.bind("", "", params)
        self.describe("P", "")
        rows: List[List[Optional[str]]] = []
        executes = 0
        tag = None
        error = None
        while tag is None and error is None:
            self.execute_portal("", max_rows)
            executes += 1
            page = 0
            while True:
                t, payload = self._recv_msg()
                if t in (b"1", b"2", b"n", b"T"):
                    continue
                if t == b"D":
                    (n,) = struct.unpack_from(">H", payload, 0)
                    pos = 2
                    row: List[Optional[str]] = []
                    for _ in range(n):
                        (ln,) = struct.unpack_from(">i", payload, pos)
                        pos += 4
                        if ln == -1:
                            row.append(None)
                        else:
                            row.append(payload[pos:pos + ln].decode())
                            pos += ln
                    rows.append(row)
                    page += 1
                    assert max_rows <= 0 or page <= max_rows, \
                        "server exceeded max_rows"
                elif t == b"s":       # PortalSuspended: Execute again
                    break
                elif t == b"C":
                    tag = payload[:-1].decode()
                    break
                elif t == b"E":
                    error = PgWireError(*self._parse_error(payload))
                    break
                else:
                    raise AssertionError(f"unexpected message {t!r}")
        self.sync()
        while True:
            t, payload = self._recv_msg()
            if t == b"Z":
                self.txn_status = payload.decode()
                break
        if error is not None:
            raise error
        return rows, executes, tag

    def extended_query(self, sql: str,
                       params: Optional[List[Optional[str]]] = None
                       ) -> QueryResult:
        """Full Parse/Bind/Describe/Execute/Sync cycle — what psycopg2 /
        JDBC do for every parameterized execute()."""
        self.parse("", sql)
        self.bind("", "", params)
        self.describe("P", "")
        self.execute_portal("")
        self.sync()
        cur = QueryResult()
        param_desc = None
        error = None
        while True:
            t, payload = self._recv_msg()
            if t in (b"1", b"2", b"3", b"n"):
                continue
            if t == b"t":
                (n,) = struct.unpack_from(">H", payload, 0)
                param_desc = list(struct.unpack_from(f">{n}I", payload, 2))
                continue
            if t == b"T":
                cur.columns = []
                (n,) = struct.unpack_from(">H", payload, 0)
                pos = 2
                for _ in range(n):
                    end = payload.index(b"\x00", pos)
                    (oid,) = struct.unpack_from(">I", payload, end + 7)
                    cur.columns.append((payload[pos:end].decode(), oid))
                    pos = end + 19
            elif t == b"D":
                (n,) = struct.unpack_from(">H", payload, 0)
                pos = 2
                row: List[Optional[str]] = []
                for _ in range(n):
                    (ln,) = struct.unpack_from(">i", payload, pos)
                    pos += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[pos:pos + ln].decode())
                        pos += ln
                cur.rows.append(row)
            elif t == b"C":
                cur.tag = payload[:-1].decode()
            elif t == b"I":
                pass
            elif t == b"E":
                error = PgWireError(*self._parse_error(payload))
            elif t == b"Z":
                self.txn_status = payload.decode()
                if error is not None:
                    raise error
                cur.param_oids = param_desc
                return cur
            else:
                raise AssertionError(f"unexpected message {t!r}")

    def close(self) -> None:
        try:
            self.sock.sendall(b"X" + struct.pack(">I", 4))
        except OSError:
            pass
        self.sock.close()
