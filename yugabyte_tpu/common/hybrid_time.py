"""HybridTime / DocHybridTime / HybridClock.

Capability parity with the reference's hybrid logical clocks:
 - HybridTime (ref: src/yb/common/hybrid_time.h:64): 64-bit value =
   physical microseconds << 12 | 12-bit logical component.
 - DocHybridTime (ref: src/yb/common/doc_hybrid_time.h:50): HybridTime +
   write_id (index of the write within one Raft batch), encoded *descending*
   at the end of each DocDB key.
 - HybridClock (ref: src/yb/server/hybrid_clock.h:88): monotonic hybrid clock
   combining wall time with a logical counter.

TPU-first divergence: the reference encodes DocHybridTime with
descending-signed varints (doc_hybrid_time.cc:50, kNumBitsForHybridTimeSize).
We use a FIXED-WIDTH 12-byte encoding (8B ~hybrid_time, 4B ~write_id, both
big-endian bitwise complements) so that keys decompose into fixed-stride
integer slabs the TPU can sort/decode without byte-granular varint parsing.
Order semantics are identical: later times sort FIRST (descending).
"""

from __future__ import annotations

import struct
import threading
import time
from functools import total_ordering

kBitsForLogicalComponent = 12
_LOGICAL_MASK = (1 << kBitsForLogicalComponent) - 1
_U64 = (1 << 64) - 1
_U32 = (1 << 32) - 1

ENCODED_DOC_HT_SIZE = 12  # bytes: 8 (ht complement) + 4 (write_id complement)


@total_ordering
class HybridTime:
    """64-bit hybrid timestamp: (physical_micros << 12) | logical.

    A plain __slots__ class, not a dataclass: one HybridTime is built per
    KV on every write and read path, and frozen-dataclass __init__ was the
    single hottest line of the ingest profile. Value-semantics (eq / hash /
    total order) are preserved; treat instances as immutable."""

    __slots__ = ("value",)

    def __init__(self, value: int = 0):
        self.value = value

    @staticmethod
    def from_micros(micros: int, logical: int = 0) -> "HybridTime":
        return HybridTime((micros << kBitsForLogicalComponent) | logical)

    def __eq__(self, other) -> bool:
        return isinstance(other, HybridTime) and self.value == other.value

    def __hash__(self) -> int:
        return hash((HybridTime, self.value))

    @property
    def physical_micros(self) -> int:
        return self.value >> kBitsForLogicalComponent

    @property
    def logical(self) -> int:
        return self.value & _LOGICAL_MASK

    def incremented(self) -> "HybridTime":
        return HybridTime(self.value + 1)

    def decremented(self) -> "HybridTime":
        return HybridTime(self.value - 1)

    @property
    def is_valid(self) -> bool:
        return self.value != _U64

    def __lt__(self, other: "HybridTime") -> bool:
        return self.value < other.value

    def __repr__(self) -> str:
        return f"HT({self.physical_micros},{self.logical})"


HybridTime.kMin = HybridTime(0)
HybridTime.kMax = HybridTime(_U64 - 1)
HybridTime.kInvalid = HybridTime(_U64)


@total_ordering
class DocHybridTime:
    """HybridTime + write_id; sorts by (ht, write_id), encoded descending
    in keys. __slots__ value class for the same hot-path reason as
    HybridTime; treat instances as immutable."""

    __slots__ = ("ht", "write_id")

    def __init__(self, ht: HybridTime = None, write_id: int = 0):
        self.ht = ht if ht is not None else HybridTime(0)
        self.write_id = write_id

    def __eq__(self, other) -> bool:
        return (isinstance(other, DocHybridTime)
                and self.ht.value == other.ht.value
                and self.write_id == other.write_id)

    def __hash__(self) -> int:
        return hash((DocHybridTime, self.ht.value, self.write_id))

    def encoded(self) -> bytes:
        """Fixed 12-byte descending encoding (see module docstring)."""
        return struct.pack(">QI", self.ht.value ^ _U64, self.write_id ^ _U32)

    @staticmethod
    def decode(data: bytes) -> "DocHybridTime":
        ht_c, wid_c = struct.unpack(">QI", data[:ENCODED_DOC_HT_SIZE])
        return DocHybridTime(HybridTime(ht_c ^ _U64), wid_c ^ _U32)

    @staticmethod
    def decode_from_end(key: bytes) -> "DocHybridTime":
        """Decode from the tail of an encoded key (ref: ht.DecodeFromEnd,
        docdb_compaction_filter.cc:123). Fixed width makes this O(1)."""
        return DocHybridTime.decode(key[-ENCODED_DOC_HT_SIZE:])

    def _tuple(self):
        return (self.ht.value, self.write_id)

    def __lt__(self, other: "DocHybridTime") -> bool:
        return self._tuple() < other._tuple()

    def __repr__(self) -> str:
        return f"DocHT({self.ht!r},w{self.write_id})"


DocHybridTime.kMin = DocHybridTime(HybridTime.kMin, 0)
DocHybridTime.kMax = DocHybridTime(HybridTime.kMax, _U32 - 1)


class HybridClock:
    """Monotonic hybrid clock (ref: src/yb/server/hybrid_clock.h:88).

    now() returns a HybridTime that is strictly increasing: physical wall
    micros when wall time advances, else bumps the logical component.
    update(ht) incorporates a remote timestamp (message receipt), keeping the
    clock ahead of everything it has seen — the core HLC rule.
    """

    def __init__(self, time_source=None):
        self._time_source = time_source or (lambda: int(time.time() * 1e6))
        self._last = HybridTime(0)
        self._lock = threading.Lock()

    def now(self) -> HybridTime:
        with self._lock:
            physical = self._time_source()
            candidate = HybridTime.from_micros(physical)
            if candidate.value <= self._last.value:
                candidate = self._last.incremented()
            self._last = candidate
            return candidate

    def update(self, seen: HybridTime) -> None:
        with self._lock:
            if seen.value > self._last.value:
                self._last = seen

    def max_global_now(self) -> HybridTime:
        # Clock-skew bound for read-time selection; static 500ms like the
        # reference's max_clock_skew_usec default.
        return HybridTime.from_micros(self._time_source() + 500_000)
