"""MemTracker: hierarchical memory accounting with limits and GC hooks.

Capability parity with the reference's MemTracker tree (ref:
src/yb/util/mem_tracker.h:139 — consumption propagates from a tracker up
through its ancestors to a process root; limits are checked root-down on
TryConsume; GarbageCollectors registered on a tracker are invoked to shed
cache memory before a consume is rejected; soft-limit checks give early
backpressure below the hard limit, ref mem_tracker.cc:557-589).

TPU-native differences: the process root's consumption functor reads the
OS RSS (the reference polls tcmalloc's generic.current_allocated_bytes,
mem_tracker.h:163 — no tcmalloc here), and HBM budgets (DeviceSlabCache)
hang off their own subtree so host-RAM arbitration never counts device
bytes against the host limit.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from yugabyte_tpu.utils import flags

flags.define_flag("memory_limit_bytes", 0,
                  "hard memory limit for the process root tracker; 0 = "
                  "derive from memory_limit_fraction of total RAM "
                  "(ref flag memory_limit_hard_bytes)")
flags.define_flag("memory_limit_fraction", 0.85,
                  "fraction of total system RAM used when "
                  "memory_limit_bytes is 0 (ref default_memory_limit_to_ram_ratio)")
flags.define_flag("memory_limit_soft_percentage", 85,
                  "percentage of the hard limit where soft-limit "
                  "backpressure begins (ref memory_limit_soft_percentage)")


def _total_system_ram() -> int:
    try:
        import os
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return 8 << 30


def _process_rss() -> int:
    """Resident set size of this process (the root consumption functor)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        import os
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class MemTracker:
    """One node in the tracker tree. Thread-safe.

    consumption is either the local tally maintained by consume()/release()
    plus all children (tally mode), or the value of ``consumption_fn``
    (functor mode, used for the process root and for caches that know
    their own usage, ref mem_tracker.h:107-112).
    """

    def __init__(self, limit: int, tracker_id: str,
                 parent: Optional["MemTracker"] = None,
                 consumption_fn: Optional[Callable[[], int]] = None,
                 add_to_parent: bool = True,
                 metric_entity=None):
        self.id = tracker_id
        self.limit = limit          # < 0 or 0 = unlimited
        self.parent = parent if add_to_parent else None
        self._consumption_fn = consumption_fn
        self._consumed = 0
        self._peak = 0
        self._lock = threading.Lock()
        self._create_lock = threading.Lock()  # serializes child creation
        self._children: Dict[str, "MemTracker"] = {}
        self._gc_fns: List[Callable[[int], None]] = []
        # ancestor chain (self first) — limits are enforced along it
        self._chain: List["MemTracker"] = [self]
        p = self.parent
        while p is not None:
            self._chain.append(p)
            p = p.parent
        if self.parent is not None:
            with self.parent._lock:
                self.parent._children[tracker_id] = self
        self._gauge = None
        if metric_entity is not None:
            self._gauge = metric_entity.gauge(
                f"mem_tracker_{tracker_id}_bytes",
                f"bytes tracked by {tracker_id}")

    # ------------------------------------------------------------ hierarchy
    def find_child(self, tracker_id: str) -> Optional["MemTracker"]:
        with self._lock:
            return self._children.get(tracker_id)

    def find_or_create_child(self, tracker_id: str, limit: int = 0,
                             consumption_fn=None) -> "MemTracker":
        # _create_lock (not _lock) spans check+create: MemTracker.__init__
        # itself takes self._lock to insert, so holding _lock here would
        # deadlock, but without serialization two racing callers would each
        # construct a child and one would be silently overwritten
        with self._create_lock:
            with self._lock:
                existing = self._children.get(tracker_id)
            if existing is not None:
                return existing
            return MemTracker(limit, tracker_id, parent=self,
                              consumption_fn=consumption_fn)

    def unregister_from_parent(self) -> None:
        """Drop the parent's reference (ref mem_tracker.h:192): the tracker
        keeps functioning standalone and a new same-id child may be created.
        Releases this subtree's tally from all ancestors and SEVERS the
        ancestor chain, so later consume/release on the orphan can no longer
        touch ex-ancestor accounting."""
        if self.parent is None:
            return
        with self._lock:
            tally = self._consumed
        if tally:
            for t in self._chain[1:]:
                t._add(-tally)
        with self.parent._lock:
            if self.parent._children.get(self.id) is self:
                del self.parent._children[self.id]
        self.parent = None
        self._chain = [self]
        self._reroot_descendants()

    def _reroot_descendants(self) -> None:
        """Truncate every descendant's ancestor chain at this tracker, so
        the whole detached subtree stops propagating into ex-ancestors."""
        with self._lock:
            children = list(self._children.values())
        for c in children:
            c._chain = [c] + self._chain
            c._reroot_descendants()

    # ---------------------------------------------------------- accounting
    def _add(self, n: int) -> None:
        with self._lock:
            self._consumed += n
            if self._consumed > self._peak:
                self._peak = self._consumed
        if self._gauge is not None:
            self._gauge.set(self._consumed)

    def consume(self, n: int) -> None:
        if n == 0:
            return
        for t in self._chain:
            t._add(n)

    def release(self, n: int) -> None:
        self.consume(-n)

    def _functor_extra(self) -> int:
        """Bytes visible only through functor-mode descendants. Tally-mode
        descendants already propagated into this tracker's _consumed via
        consume(); functor-mode ones (caches, memstores) never call it."""
        with self._lock:
            children = list(self._children.values())
        total = 0
        for c in children:
            if c._consumption_fn is not None:
                total += c.consumption()
            else:
                total += c._functor_extra()
        return total

    def consumption(self) -> int:
        if self._consumption_fn is not None:
            return int(self._consumption_fn())
        with self._lock:
            tally = self._consumed
        return tally + self._functor_extra()

    def peak_consumption(self) -> int:
        with self._lock:
            return self._peak

    def spare_capacity(self) -> int:
        """Bytes left before the tightest limit along the ancestor chain."""
        spare = None
        for t in self._chain:
            if t.limit > 0:
                s = t.limit - t.consumption()
                spare = s if spare is None else min(spare, s)
        return spare if spare is not None else (1 << 62)

    def try_consume(self, n: int) -> bool:
        """Atomically-enough consume n, honouring every ancestor limit.

        On a would-exceed, runs GC functions on the offending tracker and
        rechecks once (ref mem_tracker.cc LimitExceeded -> GcMemory)."""
        if n <= 0:
            self.consume(n)
            return True
        for t in self._chain:
            if t.limit > 0 and t.consumption() + n > t.limit:
                t._gc(t.consumption() + n - t.limit)
                if t.consumption() + n > t.limit:
                    return False
        self.consume(n)
        return True

    def limit_exceeded(self) -> bool:
        for t in self._chain:
            if t.limit > 0 and t.consumption() > t.limit:
                t._gc(t.consumption() - t.limit)
                if t.consumption() > t.limit:
                    return True
        return False

    def soft_limit_exceeded(self) -> "SoftLimitResult":
        """Early backpressure below the hard limit (ref mem_tracker.cc:557).

        Deterministic design (the reference rejects *probabilistically*
        between soft and hard): exceeded once consumption crosses
        soft_pct% of the limit; callers shed load or flush."""
        soft_pct = flags.get_flag("memory_limit_soft_percentage") / 100.0
        worst = SoftLimitResult(False, 0.0)
        for t in self._chain:
            if t.limit > 0:
                pct = t.consumption() / t.limit
                if pct > worst.current_capacity_pct:
                    worst = SoftLimitResult(pct >= soft_pct, pct)
        return worst

    # ------------------------------------------------------------------ GC
    def add_gc_function(self, fn: Callable[[int], None]) -> None:
        """fn(required_bytes) should free at least required_bytes if it can
        (ref GarbageCollector::CollectGarbage, mem_tracker.h:66)."""
        with self._lock:
            self._gc_fns.append(fn)

    def remove_gc_function(self, fn: Callable[[int], None]) -> None:
        with self._lock:
            if fn in self._gc_fns:
                self._gc_fns.remove(fn)

    def _gc(self, required: int) -> None:
        with self._lock:
            fns = list(self._gc_fns)
        for fn in fns:
            try:
                fn(required)
            except Exception:
                pass

    # ------------------------------------------------------------ reporting
    def log_usage(self, indent: int = 0) -> str:
        lines = [f"{' ' * indent}{self.id}: consumption={self.consumption()} "
                 f"peak={self.peak_consumption()} "
                 f"limit={self.limit if self.limit > 0 else 'none'}"]
        with self._lock:
            children = list(self._children.values())
        for c in children:
            lines.append(c.log_usage(indent + 2))
        return "\n".join(lines)

    def tree_json(self) -> dict:
        with self._lock:
            children = list(self._children.values())
        return {"id": self.id, "consumption": self.consumption(),
                "peak": self.peak_consumption(),
                "limit": self.limit if self.limit > 0 else None,
                "children": [c.tree_json() for c in children]}


class SoftLimitResult:
    __slots__ = ("exceeded", "current_capacity_pct")

    def __init__(self, exceeded: bool, pct: float):
        self.exceeded = exceeded
        self.current_capacity_pct = pct


class ScopedTrackedConsumption:
    """RAII consumption guard (ref mem_tracker.h ScopedTrackedConsumption):
    use as a context manager, or keep + reset(new_size) as it changes."""

    def __init__(self, tracker: MemTracker, n: int):
        self._tracker = tracker
        self._n = n
        tracker.consume(n)

    def reset(self, new_n: int) -> None:
        self._tracker.consume(new_n - self._n)
        self._n = new_n

    def release(self) -> None:
        if self._tracker is not None:
            self._tracker.release(self._n)
            self._tracker = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


_root_lock = threading.Lock()
_root: Optional[MemTracker] = None


def root_tracker() -> MemTracker:
    """The process root, lazily created; limit from flags, consumption from
    RSS (the reference's root polls tcmalloc, mem_tracker.cc:239-260)."""
    global _root
    with _root_lock:
        if _root is None:
            limit = flags.get_flag("memory_limit_bytes")
            if not limit:
                limit = int(_total_system_ram()
                            * flags.get_flag("memory_limit_fraction"))
            _root = MemTracker(limit, "root", consumption_fn=_process_rss)
        return _root


def reset_root_for_tests() -> None:
    global _root
    with _root_lock:
        _root = None
