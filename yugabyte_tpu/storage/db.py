"""DB: the LSM storage engine facade.

Capability parity with the reference's DBImpl as YB uses it (ref:
src/yb/rocksdb/db/db_impl.cc): WAL-less writes (the Raft log is the WAL and
the Raft index becomes the sequence/frontier — ref: tablet/tablet.cc:1247-1260),
memtable -> flush -> universal compaction, manifest recovery, checkpoints.
Reads merge memtable + SSTs (ref: MergingIterator table/merger.cc:51 — here a
heapq.merge over sorted sources, since point/short reads stay on CPU; large
scans go through the TPU scan kernel in ops/scan.py).
"""

from __future__ import annotations

import heapq
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Iterator, List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import split_key_and_ht
from yugabyte_tpu.docdb.value_type import ValueType
from yugabyte_tpu.ops.slabs import pack_doc_ht
from yugabyte_tpu.storage import compaction as compaction_mod
from yugabyte_tpu.storage.memtable import (MemTable, make_internal_key,
                                           new_memtable)
from yugabyte_tpu.storage.sst import (
    BlockCache, Frontier, SSTReader, SSTWriter, data_file_name)
from yugabyte_tpu.storage.version_set import VersionSet
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.utils.threadpool import PriorityThreadPool
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.utils import lock_rank

flags.define_flag("memstore_size_bytes", 128 * 1024 * 1024,
                  "flush memtable at this size (ref docdb_rocksdb_util.cc:113)")
flags.define_flag("memtable_native", True,
                  "Use the C++ memtable arena (native/memtable_arena.cc) "
                  "when the toolchain is available")
flags.define_flag("read_native", True,
                  "serve point reads and scans through the native read "
                  "engine (native/read_engine.cc) when it builds; the "
                  "Python merge path remains the fallback (ref: "
                  "block_based_table_reader.cc:1144-1286)")
flags.define_flag("point_read_batched", True,
                  "resolve DB.multi_get through the batched device "
                  "kernels (ops/point_read.py) when a device + slab "
                  "cache are configured; the native per-key path is the "
                  "byte-identical fallback")
flags.define_flag("point_read_learned_index", True,
                  "seed the batched locate kernel with persisted "
                  "learned per-SST indexes (advisory; mispredictions "
                  "fall back to the exact seek)")


def _storage_metrics():
    """Process-wide read/scan tier histograms (ref: the reference's
    rocksdb_db_get_micros / db_iter latency metrics)."""
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    e = ROOT_REGISTRY.entity("server", "storage")
    return (e.histogram("db_get_duration_ms",
                        "point-read latency through DB.get"),
            e.histogram("db_scan_duration_ms",
                        "full device-scan latency through DB.scan_visible"),
            e.histogram("db_multi_get_duration_ms",
                        "batched point-read latency through DB.multi_get"))


class CompactionStats:
    """Per-DB compaction/flush accounting — the `/compactionz` analogue of
    RocksDB's GetProperty("rocksdb.stats") (ref: rocksdb/db/
    internal_stats.cc). Running write amplification is
    (flush bytes + compaction bytes written) / flush bytes: how many times
    each ingested byte is rewritten by the LSM."""

    def __init__(self):
        self._lock = threading.Lock()
        self.flushes = 0
        self.flush_bytes_written = 0
        self.flush_rows = 0
        self.compactions = 0
        self.compaction_bytes_read = 0
        self.compaction_bytes_written = 0
        self.compaction_files_in = 0
        self.compaction_files_out = 0
        self.compaction_rows_in = 0
        self.compaction_rows_out = 0
        self.versions_gcd = 0          # input entries dropped by MVCC GC
        self.tombstones_written = 0    # TTL expiries rewritten as tombstones

    def record_flush(self, nbytes: int, rows: int) -> None:
        with self._lock:
            self.flushes += 1
            self.flush_bytes_written += nbytes
            self.flush_rows += rows

    def record_compaction(self, bytes_read: int, bytes_written: int,
                          files_in: int, files_out: int,
                          rows_in: int, rows_out: int,
                          tombstones_written: int = 0) -> None:
        with self._lock:
            self.compactions += 1
            self.compaction_bytes_read += bytes_read
            self.compaction_bytes_written += bytes_written
            self.compaction_files_in += files_in
            self.compaction_files_out += files_out
            self.compaction_rows_in += rows_in
            self.compaction_rows_out += rows_out
            self.versions_gcd += max(0, rows_in - rows_out)
            self.tombstones_written += tombstones_written

    def to_dict(self) -> dict:
        with self._lock:
            ingested = self.flush_bytes_written
            write_amp = ((ingested + self.compaction_bytes_written)
                         / ingested if ingested else 0.0)
            return {
                "flushes": self.flushes,
                "flush_bytes_written": self.flush_bytes_written,
                "flush_rows": self.flush_rows,
                "compactions": self.compactions,
                "compaction_bytes_read": self.compaction_bytes_read,
                "compaction_bytes_written": self.compaction_bytes_written,
                "compaction_files_in": self.compaction_files_in,
                "compaction_files_out": self.compaction_files_out,
                "compaction_rows_in": self.compaction_rows_in,
                "compaction_rows_out": self.compaction_rows_out,
                "versions_gcd": self.versions_gcd,
                "tombstones_written": self.tombstones_written,
                "write_amplification": round(write_amp, 3),
            }


@dataclass
class DBOptions:
    block_entries: Optional[int] = None
    block_cache: Optional[BlockCache] = None
    compaction_pool: Optional[PriorityThreadPool] = None
    device: object = None  # JAX device for compaction kernels
    # jax.sharding.Mesh over >1 device: large compactions fan their
    # subcompactions across it (parallel/dist_compact.py); None = single
    # device (ref: subcompaction threads, compaction_job.cc:456-468)
    mesh: object = None
    # tserver/compaction_pool.CompactionPool: when set, device-routed
    # compactions are scheduled through the mesh-sharded multi-tablet
    # pool (batch-slot waves / whole-mesh dist jobs) instead of running
    # the device stage inline on this DB's compaction thread
    mesh_pool: object = None
    # measured device-vs-native router (storage/offload_policy.py)
    offload_policy: object = None
    # HBM-resident slab cache (storage/device_cache.py); shared across
    # tablets like the reference's server-wide block cache
    device_cache: object = None
    # returns current history cutoff HT value (ref: tablet_retention_policy.h:29)
    retention_policy: Callable[[], int] = lambda: 0
    memstore_size_bytes: Optional[int] = None
    auto_compact: bool = True


_OVERLAY_TOO_BIG = object()  # sentinel: memtable too large to repack


class DB:
    def __init__(self, db_dir: str, options: Optional[DBOptions] = None):
        self.db_dir = db_dir
        self.opts = options or DBOptions()
        # RocksDB-style background-error slot (ref: db_impl.cc
        # error_handler_): a failed flush/compaction parks the DB in
        # degraded read-only mode — writes reject retryably, reads keep
        # serving the installed state — until retry_background_work()
        # clears it. The hook tells the owner (TabletPeer) to transition
        # the tablet to FAILED.
        self._bg_error: Optional["Status"] = None
        self.on_background_error: Optional[Callable[[object], None]] = None
        self._writing: set = set()  # SST paths mid-write (orphan-sweep guard)
        self._device_cache = None
        if self.opts.device_cache is not None:
            from yugabyte_tpu.storage.device_cache import (
                DeviceSlabCache, NamespacedSlabCache)
            # namespace file ids per DB under the shared server-wide cache
            # (kept off self.opts: DBOptions may be shared between DBs)
            self._device_cache = (
                NamespacedSlabCache(self.opts.device_cache, os.path.abspath(db_dir))
                if isinstance(self.opts.device_cache, DeviceSlabCache)
                else self.opts.device_cache)
        # host-side packed-run cache: flush/compaction outputs retained
        # decoded so steady-state compactions skip read+decode entirely
        # (storage/run_cache.py; None when disabled or no native engine).
        # Only the device+native combined compaction path consumes it
        # (compaction.py:196 needs device_cache + a device kernel), so a
        # native-only or deviceless DB must not pay the per-flush survivor
        # copy and pinned host RAM for a cache nothing ever reads.
        self._run_cache = None
        if self._device_cache is not None and \
                self.opts.device not in (None, "native"):
            from yugabyte_tpu.storage.run_cache import (NamespacedRunCache,
                                                        shared_run_cache)
            _rc = shared_run_cache()
            if _rc is not None:
                self._run_cache = NamespacedRunCache(
                    _rc, os.path.abspath(db_dir))
        os.makedirs(db_dir, exist_ok=True)
        self.compaction_stats = CompactionStats()
        self.versions = VersionSet(db_dir)
        self.versions.recover()
        self.mem = new_memtable()
        self._imm: Optional[MemTable] = None   # guarded-by: _lock; memtable being flushed
        self._readers: dict = {}
        self._lock = lock_rank.tracked(threading.RLock(), "db._lock")
        self._compacting = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # Cancellation seam for in-flight background work: close() and a
        # tablet-FAILED transition (cancel_background_work) flip it, and
        # the compaction pipeline checks it at every stage boundary — an
        # in-flight offloaded job aborts cleanly (partial outputs swept,
        # staging leases released, nothing installed) instead of racing
        # shutdown to the filesystem.
        from yugabyte_tpu.utils.cancellation import CancellationToken
        self._cancel = CancellationToken(f"compaction@{db_dir}")
        self._pins: dict = {}       # file_id -> active scan count
        self._obsolete: dict = {}   # file_id -> reader awaiting unpin+delete
        # Runs after the memtable swap, before this DB's SST installs. The
        # tablet points the intents DB's hook at regular_db.flush so the
        # intents flushed frontier never persists ahead of the regular DB
        # for ops spanning both (bootstrap replays from the min frontier;
        # an OP_UPDATE_TXN whose intent tombstones persisted but whose
        # regular-DB rows didn't would replay as a no-op and lose data).
        self.pre_flush_hook: Optional[Callable[[], None]] = None
        # native read engine state: per-SST native handles + a frozen
        # ReaderSet snapshot, both rebuilt when the live-file set changes
        self._native_readers: dict = {}
        self._rset = None
        self._rset_gen = 0  # bumped on every invalidation: a ReaderSet
        #                     built against gen G installs only if still G
        self._mem_run_cache: Optional[Tuple[int, int, object]] = None
        for fm in self.versions.live_files():
            self._readers[fm.file_id] = SSTReader(fm.path, self.opts.block_cache)

    def memstore_bytes(self) -> int:
        """Mutable + flushing memtable bytes (global-memstore arbitration)."""
        with self._lock:
            total = self.mem.approximate_bytes
            if self._imm is not None:
                total += self._imm.approximate_bytes
            return total

    def oldest_memstore_write_s(self) -> Optional[float]:
        with self._lock:
            times = [self.mem.oldest_write_s]
            if self._imm is not None:
                times.append(self._imm.oldest_write_s)
        times = [t for t in times if t is not None]
        return min(times) if times else None

    def approx_entry_count(self) -> int:
        """Cheap emptiness probe (used to skip the intent overlay on
        intent-free tablets). Zero means definitely empty."""
        with self._lock:
            if self.mem.approximate_bytes or self._imm is not None:
                return 1
            return len(self._readers)

    def approx_row_entries(self) -> int:
        """Rough live-entry count (SST props + a memtable byte-derived
        guess) — the pushdown size gate's input: a fused dispatch only
        beats the per-row host path once the scan is big enough to
        amortize dispatch + (first-time) compile cost."""
        with self._lock:
            n = sum(r.props.n_entries for r in self._readers.values())
            # ~32 bytes/entry is the right order for the gate's purpose
            n += self.mem.approximate_bytes // 32
            if self._imm is not None:
                n += self._imm.approximate_bytes // 32
            return n

    def has_deep_files(self) -> bool:
        """Any live SST holding documents deeper than row+column — the
        tablet's gate for the flat batched row-read fast path (deep rows
        cannot be reconstructed from enumerated column probes)."""
        with self._lock:
            return any(r.props.has_deep for r in self._readers.values())

    def mem_entries_range(self, lower: bytes, upper: bytes
                          ) -> List[Tuple[bytes, bytes]]:
        """Memtable(+imm) entries with lower <= internal_key < upper —
        the host-side row probe of the tablet's batched read (catches
        recent deep/unknown-subkey writes that exact-key probes of the
        enumerated schema columns would miss)."""
        with self._lock:
            mems = [self.mem] + ([self._imm] if self._imm is not None
                                 else [])
        out: List[Tuple[bytes, bytes]] = []
        for m in mems:
            out.extend(m.entries_range(lower, upper))
        return out

    # ------------------------------------------------------- background error
    @property
    def background_error(self):
        """The parked Status, or None when healthy."""
        return self._bg_error

    def _require_writable(self) -> None:
        err = self._bg_error
        if err is not None:
            from yugabyte_tpu.utils.status import Status, StatusError
            raise StatusError(Status.ServiceUnavailable(
                f"DB {self.db_dir} is read-only after a background error "
                f"({err}); retry later"))

    def _set_background_error(self, where: str, exc: BaseException,
                              corruption: bool = False) -> None:
        from yugabyte_tpu.utils.status import Code, Status
        if corruption:
            st = Status.Corruption(
                f"{where} detected corrupt data in {self.db_dir}: {exc}")
        else:
            st = Status.IoError(f"{where} failed in {self.db_dir}: {exc}")
        with self._lock:
            if self._bg_error is not None:
                # first error wins — except corruption, which UPGRADES a
                # retryable I/O park: lost bytes need a rebuild, and the
                # sticky corruption code is what blocks in-place retry
                if not corruption or \
                        self._bg_error.code == Code.CORRUPTION:
                    return
            self._bg_error = st
        TRACE("db %s: background error (%s): %s", self.db_dir, where, exc)
        cb = self.on_background_error
        if cb is not None:
            cb(st)

    def cancel_background_work(self, reason: str = "shutdown") -> None:
        """Abort in-flight background compactions at their next stage
        boundary (tablet-FAILED transition, shutdown). One-way until
        retry_background_work re-arms a fresh token."""
        self._cancel.cancel(reason)

    def retry_background_work(self) -> bool:
        """Clear the parked error and retry the failed work (the
        maintenance manager drives this with capped backoff, ref
        DBImpl::Resume). Returns True when the DB is healthy again; a
        failing retry re-parks it. A CORRUPTION error is STICKY: lost
        bytes cannot be retried back into existence — the replica must
        be rebuilt from a healthy peer (remote bootstrap)."""
        from yugabyte_tpu.utils.status import Code
        with self._lock:
            if self._bg_error is not None \
                    and self._bg_error.code == Code.CORRUPTION:
                return False
            if self._cancel.cancelled and not self._closed:
                # recovery re-arms the cancellation seam for the retried
                # background work (the old token is permanently tripped;
                # re-armed even without a parked error — a tablet-FAILED
                # cancel may have fired without this DB itself erroring)
                from yugabyte_tpu.utils.cancellation import (
                    CancellationToken)
                self._cancel = CancellationToken(
                    f"compaction@{self.db_dir}")
            if self._bg_error is None:
                return True
            self._bg_error = None
        from yugabyte_tpu.utils.status import StatusError
        try:
            self.flush()
        except (OSError, StatusError):
            return False  # flush's failure path re-set the background error
        if self.opts.auto_compact:
            self.maybe_schedule_compaction()
        return self._bg_error is None

    def _sweep_orphan_outputs_unlocked(self) -> None:
        """Remove SST files on disk that no version references and no
        in-flight writer owns — the partial outputs of a failed
        flush/compaction (ref: PurgeObsoleteFiles after a failed job)."""
        try:
            names = os.listdir(self.db_dir)
        except OSError as e:
            # sweep runs again next retry cycle, but a silent skip hid
            # e.g. a permissions regression — surface it
            TRACE("db %s: orphan sweep cannot list dir: %s",
                  self.db_dir, e)
            return
        live = set(self.versions.files)
        writing = {os.path.basename(p) for p in self._writing}
        for name in names:
            stem = name.split(".", 1)[0]
            if not (name.endswith(".sst") or name.endswith(".sblock.0")) \
                    or not stem.isdigit():
                continue
            base_name = stem + ".sst"
            if int(stem) in live or base_name in writing:
                continue
            try:
                os.remove(os.path.join(self.db_dir, name))
            except OSError as e:
                # an orphan that cannot be removed leaks disk until some
                # later sweep succeeds — keep trying, but say so
                TRACE("db %s: orphan sweep cannot remove %s: %s",
                      self.db_dir, name, e)

    # ------------------------------------------------------------------ write
    def _post_write_locked(self, op_id: Tuple[int, int]) -> bool:
        """Shared writer tail (lock held): op-id tracking + flush trigger."""
        self._last_op_id = max(getattr(self, "_last_op_id", (0, 0)), op_id)
        limit = self.opts.memstore_size_bytes or \
            flags.get_flag("memstore_size_bytes")
        return self.mem.approximate_bytes >= limit

    def write_batch(self, items: List[Tuple[bytes, DocHybridTime, bytes]],
                    op_id: Tuple[int, int] = (0, 0)) -> None:
        """Apply a batch (already carrying DocHybridTimes). WAL-less: durability
        comes from the Raft log above (ref: tablet.cc:1247 WriteToRocksDB)."""
        self._require_writable()
        with self._lock:
            mem = self.mem
            if len(items) > 8 or hasattr(mem, "add_columns"):
                # the native arena always takes the batch call (its add()
                # would pay a full ctypes round trip PER ROW)
                mem.add_batch(items)
            else:
                for key_prefix, dht, value in items:
                    mem.add(key_prefix, dht, value)
            need_flush = self._post_write_locked(op_id)
        # flush outside the lock: concurrent writers keep inserting into the
        # fresh memtable while the immutable one packs + writes its SST
        if need_flush:
            self.flush()

    def write_batch_columns(self, keys: List[bytes], ht, wid,
                            values: List[bytes],
                            op_id: Tuple[int, int] = (0, 0)) -> None:
        """Columnar bulk write (batched-RPC apply / bulk-load shape):
        parallel key/value lists + uint64 HT and uint32 write-id arrays —
        one native memtable call instead of per-row tuple assembly
        (ref: db/memtable.cc Add, write path hot loop)."""
        self._require_writable()
        with self._lock:
            mem = self.mem
            if hasattr(mem, "add_columns"):
                mem.add_columns(keys, ht, wid, values)
            else:
                mem.add_batch([
                    (k, DocHybridTime(HybridTime(int(h)), int(w)), v)
                    for k, h, w, v in zip(keys, ht, wid, values)])
            need_flush = self._post_write_locked(op_id)
        if need_flush:
            self.flush()

    # ---------------------------------------------------- native read engine
    def _native_rset(self):
        """Frozen native ReaderSet over the live SSTs, or None when the
        native read engine is disabled/unavailable. Snapshots outlive
        installs: in-flight scans keep the old set (and its pinned file
        bytes) alive by reference, so no file pinning is needed."""
        if not flags.get_flag("read_native"):
            return None
        rset = self._rset
        if rset is not None:  # lock-free hot path (GIL-atomic attr read;
            return rset       # stale snapshots are safe, see docstring)
        from yugabyte_tpu.storage import native_read
        if not native_read.available():
            return None
        with self._lock:
            if self._rset is not None:
                return self._rset
            gen = self._rset_gen
            readers = dict(self._readers)
            existing = dict(self._native_readers)
        built = {}
        for fid, r in readers.items():
            nr = existing.get(fid)
            built[fid] = nr if nr is not None else \
                native_read.NativeSSTReader(r)
        rset = native_read.ReaderSet(list(built.values()))
        with self._lock:
            if self._rset_gen != gen:
                # a flush/compaction installed while we built: our snapshot
                # is already stale — serve it for THIS call only (the file
                # set it holds was live and consistent), do not cache it
                return rset if self._rset is None else self._rset
            self._native_readers = built
            self._rset = rset
        return rset

    def _memtable_run(self):
        """Packed memtable(+imm) overlay for native scans, cached per
        memtable version (rebuilding per scan would re-pay per-entry
        packing on every read of a write-hot tablet)."""
        from yugabyte_tpu.docdb.value import decode_control_fields
        from yugabyte_tpu.docdb.value_type import ValueType as VT
        from yugabyte_tpu.storage.native_read import PackedRun
        with self._lock:
            mem, imm = self.mem, self._imm
        key = (id(mem), mem.version, id(imm),
               imm.version if imm is not None else -1)
        cached = self._mem_run_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        if mem.empty and (imm is None or imm.empty):
            run = None
        elif (mem.n_entries
              + (imm.n_entries if imm is not None else 0)) > 200_000:
            # write-hot tablet near the flush threshold: repacking the
            # whole memtable per scan costs more than the Python merge it
            # replaces — signal the caller to take the fallback path
            return _OVERLAY_TOO_BIG
        else:
            sources = [mem.iter_from(b"")]
            if imm is not None:
                sources.append(imm.iter_from(b""))
            entries = []
            for ikey, value in heapq.merge(*sources):
                prefix, dht = split_key_and_ht(ikey)
                fl = 0
                ttl = 0
                try:
                    _, ttl_ms, off = decode_control_fields(value)
                    tag = value[off] if off < len(value) else 0
                    if tag == VT.kTombstone:
                        fl |= 1
                    elif tag == VT.kObject:
                        fl |= 2
                    if ttl_ms is not None:
                        fl |= 4
                        ttl = ttl_ms
                except (IndexError, ValueError):
                    pass
                entries.append((prefix, dht.ht.value, dht.write_id, fl, ttl,
                                value))
            run = PackedRun(entries)
        self._mem_run_cache = (key, run)
        return run

    def scan_native(self, lower: bytes = b"", upper: Optional[bytes] = None,
                    read_ht_value: Optional[int] = None,
                    visible: bool = False, batch_rows: int = 65536,
                    internal_keys: bool = False):
        """Native streaming scan (NativeScan) over SSTs + memtable overlay,
        or None when the native engine is unavailable. visible=True
        resolves MVCC visibility in C++ (DocRowwiseIterator's RESOLVE
        stage); internal_keys=True emits full internal keys (raw mode)."""
        from yugabyte_tpu.storage.native_read import NativeScan
        # overlay snapshot BEFORE the reader set (see get(): double
        # coverage is safe, a hidden row is not)
        overlay = self._memtable_run()
        if overlay is _OVERLAY_TOO_BIG:
            return None
        rset = self._native_rset()
        if rset is None:
            return None
        mode = 1 if visible else (2 if internal_keys else 0)
        return NativeScan(
            rset, lower, upper,
            read_ht_value if read_ht_value is not None else (2**64 - 1),
            overlay=overlay, batch_rows=batch_rows, mode=mode)

    def ingest_packed(self, keys_blob: bytes, key_offs, ht, wid,
                      vals_blob: bytes, val_offs,
                      op_id: Tuple[int, int] = (0, 0)) -> Optional[int]:
        """Bulk-load one packed run directly as an L0 SST, bypassing the
        memtable (the reference's bulk-load / external-file ingestion path,
        ref: src/yb/tools/yb_bulk_load.cc,
        rocksdb/db/external_sst_file_ingestion_job.cc). Rows need not be
        pre-sorted — the native encoder orders them. Returns the file id,
        or None for an empty run. Requires the native engine (callers fall
        back to write_batch + flush)."""
        from yugabyte_tpu.storage import native_engine
        from yugabyte_tpu.storage.sst import write_sst_from_packed
        from yugabyte_tpu.utils.env import get_env
        if not (native_engine.available() and not get_env().encrypted):
            raise RuntimeError("ingest_packed requires the native engine")
        self._require_writable()
        n = len(key_offs) - 1
        if n == 0:
            return None
        with self._lock:
            fid = self.versions.new_file_id()
            self._last_op_id = max(getattr(self, "_last_op_id", (0, 0)),
                                   op_id)
        path = os.path.join(self.db_dir, f"{fid:06d}.sst")
        frontier = Frontier(op_id_min=op_id, op_id_max=op_id,
                            history_cutoff=0)
        props = write_sst_from_packed(
            path, keys_blob, key_offs, ht, wid, vals_blob, val_offs,
            frontier=frontier, block_entries=self.opts.block_entries)
        with self._lock:
            self.versions.add_file(fid, path, props)
            self._readers[fid] = SSTReader(path, self.opts.block_cache)
            self._rset = None
            self._rset_gen += 1
        if self.opts.auto_compact:
            self.maybe_schedule_compaction()
        return fid

    # ------------------------------------------------------------------ read
    def get(self, key_prefix: bytes, read_ht: Optional[HybridTime] = None
            ) -> Optional[Tuple[DocHybridTime, bytes]]:
        """Latest version of key_prefix visible at read_ht (raw KV semantics;
        document semantics layer above in docdb)."""
        import time as _time
        t0 = _time.monotonic()
        try:
            return self._get_inner(key_prefix, read_ht)
        except StatusError as e:
            self._route_read_corruption(e)
            raise
        finally:
            _storage_metrics()[0].increment(
                (_time.monotonic() - t0) * 1e3)

    def _route_read_corruption(self, e: "StatusError") -> None:
        """A read that hit corrupt SST bytes (block CRC / footer
        mismatch) must not surface as a raw Corruption to the client:
        route it to the background-error slot — parking the DB and
        failing the tablet so the master rebuilds the replica — and
        re-raise RETRYABLY so the client walks to a healthy replica."""
        from yugabyte_tpu.utils.status import Code, Status
        if e.status.code != Code.CORRUPTION:
            return
        self._set_background_error("read", e, corruption=True)
        raise StatusError(Status.ServiceUnavailable(
            f"read hit corrupt SST data in {self.db_dir} "
            f"({e.status.message}); replica is being repaired — retry "
            f"another replica")) from e

    def _get_inner(self, key_prefix: bytes,
                   read_ht: Optional[HybridTime] = None
                   ) -> Optional[Tuple[DocHybridTime, bytes]]:
        read_ht = read_ht or HybridTime.kMax
        seek = make_internal_key(key_prefix, DocHybridTime(read_ht, 0xFFFFFFFF))
        boundary = key_prefix + bytes([ValueType.kHybridTime])
        # memtable snapshot BEFORE the reader set: a flush landing between
        # the two moves entries mem -> SST, and the old MemTable object
        # still holds them, so either ordering race at worst double-covers
        # a row (newest version wins) — never hides one
        with self._lock:
            mems = [self.mem] + ([self._imm] if self._imm is not None
                                 else [])
        rset = self._native_rset()
        if rset is not None:
            # native fast path: memtable probes in Python (bisect), SSTs in
            # one native call; newest visible version wins across sources
            best = None  # (ht_value, wid, value)
            for mem in mems:
                hit = mem.point_get(seek, boundary)
                if hit is not None:
                    _, dht = split_key_and_ht(hit[0])
                    cand = (dht.ht.value, dht.write_id, hit[1])
                    if best is None or cand[:2] > best[:2]:
                        best = cand
            if rset.n:
                hit = rset.multi_get(key_prefix, -1, read_ht.value)
                if hit is not None:
                    ht_v, wid, _fl, val = hit
                    if best is None or (ht_v, wid) > best[:2]:
                        best = (ht_v, wid, val)
            if best is None:
                return None
            return DocHybridTime(HybridTime(best[0]), best[1]), best[2]
        # Bloom filters hold DOC key prefixes (storage/bloom.py): probe with
        # the DocKey portion, not the full subdoc key.
        from yugabyte_tpu.ops.slabs import _doc_key_len
        try:
            bloom_key = key_prefix[: _doc_key_len(key_prefix)]
        except Exception:
            bloom_key = None
        for ikey, value in self.iter_from(seek, check_bloom_doc=bloom_key):
            if not ikey.startswith(boundary):
                return None
            prefix, dht = split_key_and_ht(ikey)
            if prefix == key_prefix and dht.ht.value <= read_ht.value:
                return dht, value
            return None
        return None

    # ------------------------------------------------------- batched read
    def multi_get(self, keys: List[bytes],
                  read_ht: Optional[HybridTime] = None,
                  doc_key_lens: Optional[List[int]] = None
                  ) -> List[Optional[Tuple[DocHybridTime, bytes]]]:
        """Batched point reads: BYTE-IDENTICAL to
        ``[self.get(k, read_ht) for k in keys]`` (per-key MVCC at the
        shared read_ht), but the SST layer resolves the whole batch in
        vectorized device kernels over the HBM-resident slab matrices
        (ops/point_read.py: bloom probe -> block locate -> survivor
        gather) while the memtable probes stay host-side. Falls back —
        byte-identically — to the native per-key path when no device is
        configured, the batch's shape bucket is quarantined after a
        device fault, or a kernel dispatch faults mid-batch.

        doc_key_lens: optional per-key DocKey prefix lengths (the bloom
        probe's filter keys); callers that built the keys (tablet
        multi_read) pass them to skip per-key host parsing."""
        import time as _time
        t0 = _time.monotonic()
        try:
            return self._multi_get_inner(list(keys), read_ht,
                                         doc_key_lens)
        except StatusError as e:
            self._route_read_corruption(e)
            raise
        finally:
            _storage_metrics()[2].increment(
                (_time.monotonic() - t0) * 1e3)

    def _multi_get_inner(self, keys, read_ht, doc_key_lens=None):
        import time as _time
        from yugabyte_tpu.utils import latency as _latency
        read_ht = read_ht or HybridTime.kMax
        if not keys:
            return []
        if flags.get_flag("point_read_batched") \
                and self._device_cache is not None \
                and self.opts.device not in (None, "native"):
            t0 = _time.monotonic()
            res = self._multi_get_device(keys, read_ht, doc_key_lens)
            _latency.record_stage(_latency.STAGE_DEVICE_DISPATCH,
                                  (_time.monotonic() - t0) * 1e3)
            if res is not None:
                return res
        t0 = _time.monotonic()
        res = self._multi_get_native(keys, read_ht)
        _latency.record_stage(_latency.STAGE_HOST_FALLBACK,
                              (_time.monotonic() - t0) * 1e3)
        return res

    def _multi_get_native(self, keys, read_ht):
        """The CPU fallback: one native multi_get per key over a single
        reader-set snapshot (storage/native_read.py), memtable probes in
        Python — the loop body of _get_inner without the per-call
        snapshot/metric overhead. Byte-identical to sequential gets."""
        # memtable snapshot BEFORE the reader set (see get())
        with self._lock:
            mems = [self.mem] + ([self._imm] if self._imm is not None
                                 else [])
        rset = self._native_rset()
        if rset is None:
            return [self._get_inner(k, read_ht) for k in keys]
        mems = [m for m in mems if not m.empty]
        sst_hits = (rset.multi_get_many(keys, read_ht.value)
                    if rset.n else [None] * len(keys))
        mem_hits = self._mem_probe_many(mems, keys, read_ht)
        out = []
        for sh, best in zip(sst_hits, mem_hits):
            if sh is not None:
                ht_v, wid, _fl, val = sh
                if best is None or (ht_v, wid) > best[:2]:
                    best = (ht_v, wid, val)
            out.append(None if best is None else
                       (DocHybridTime(HybridTime(best[0]), best[1]),
                        best[2]))
        return out

    @staticmethod
    def _mem_probe_many(mems, keys, read_ht):
        """Newest memtable candidate per key as (ht_value, wid, value),
        via each memtable's BATCHED probe (one lock acquisition per
        memtable instead of one per key — the per-key locking dominated
        batched reads of memtable-resident rows)."""
        if not mems:
            return [None] * len(keys)
        probes = [(make_internal_key(k, DocHybridTime(read_ht, 0xFFFFFFFF)),
                   k + bytes([ValueType.kHybridTime])) for k in keys]
        best = [None] * len(keys)
        for mem in mems:
            for i, hit in enumerate(mem.point_get_many(probes)):
                if hit is None:
                    continue
                _, dht = split_key_and_ht(hit[0])
                cand = (dht.ht.value, dht.write_id, hit[1])
                if best[i] is None or cand[:2] > best[i][:2]:
                    best[i] = cand
        return best

    def _multi_get_device(self, keys, read_ht, doc_key_lens=None):
        """The batched device path, or None when this batch must take
        the native fallback (unstageable residency, quarantined shape
        bucket, or a mid-batch device fault — all byte-identical)."""
        from yugabyte_tpu.ops import device_faults, point_read
        from yugabyte_tpu.storage import offload_policy
        # memtable snapshot BEFORE the reader set (see get())
        with self._lock:
            mems = [self.mem] + ([self._imm] if self._imm is not None
                                 else [])
            readers = list(self._readers.items())
            for fid, _ in readers:
                self._pins[fid] = self._pins.get(fid, 0) + 1
        try:
            staged_by = []
            for fid, r in readers:
                if r.props.n_entries == 0:
                    continue
                st = self._device_cache.get(fid)
                if st is None:
                    # write-through on miss, like scan_visible: the next
                    # batch over this file finds it resident
                    try:
                        st = self._device_cache.stage(fid, r.read_all(),
                                                      for_read=True)
                    except StatusError:
                        raise  # corrupt block: multi_get routes + re-raises
                if st.n != r.props.n_entries:
                    return None  # stale residency: let native serve
                staged_by.append((fid, r, st))
            from yugabyte_tpu.storage.bucket_health import health_board
            board = health_board()
            if any(not board.allow_device(
                    "point_read_locate",
                    offload_policy.point_read_bucket_key(st.n_pad))
                   for _fid, _r, st in staged_by):
                return None
            results: List = [None] * len(keys)
            cur = {"n_pad": staged_by[0][2].n_pad if staged_by else 0}
            import time as _time
            t0 = _time.monotonic()
            try:
                self._multi_get_device_batches(
                    keys, read_ht, mems, staged_by, results,
                    doc_key_lens, cur)
                if staged_by:
                    board.record_device(
                        "point_read_locate",
                        offload_policy.point_read_bucket_key(
                            cur["n_pad"]),
                        len(keys), _time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — device-fault containment
                if not device_faults.is_device_fault(e):
                    raise
                # fault containment: park the shape bucket and serve this
                # batch (and the quarantine window) via the native path,
                # byte-identically — mirrors the compaction fallback
                board.record_fault(
                    "point_read_locate",
                    offload_policy.point_read_bucket_key(cur["n_pad"]),
                    reason=f"point-read {type(e).__name__}: {e}")
                point_read.point_read_metrics()[
                    "device_fallbacks"].increment()
                TRACE("multi_get: device fault mid-batch (%r) — shape "
                      "bucket (1, %d) quarantined; serving natively",
                      e, cur["n_pad"])
                return None
            return results
        finally:
            with self._lock:
                for fid, _ in readers:
                    self._pins[fid] -= 1
                    if not self._pins[fid]:
                        del self._pins[fid]
                self._purge_obsolete_unlocked()

    def _multi_get_device_batches(self, keys, read_ht, mems, staged_by,
                                  results, doc_key_lens, cur):
        import numpy as np
        from yugabyte_tpu.ops import point_read
        from yugabyte_tpu.ops.slabs import _doc_key_len
        from yugabyte_tpu.storage import learned_index
        metrics = point_read.point_read_metrics()
        mems = [m for m in mems if not m.empty]
        use_model = flags.get_flag("point_read_learned_index")
        for start in range(0, len(keys), 1024):
            chunk = keys[start: start + 1024]
            b = len(chunk)
            b_pad = point_read.batch_bucket(b)
            metrics["batches"].increment()
            metrics["keys"].increment(b)
            metrics["batch_rows"].increment(b)
            # bloom hashes over the DocKey prefixes — one device FNV
            # dispatch per chunk (storage/bloom.py is the CPU twin)
            if doc_key_lens is not None:
                dkls = doc_key_lens[start: start + 1024]
            else:
                dkls = [_doc_key_len(k) for k in chunk]
            max_dkl = max(dkls) if dkls else 1
            from yugabyte_tpu.ops.run_merge import quantize_width
            w_hash = quantize_width(max(1, -(-max_dkl // 4)))
            hw, _hl = point_read.pack_query_batch(chunk, w_hash)
            dk_pad = np.zeros(b_pad, dtype=np.int32)
            dk_pad[:b] = dkls
            h1, h2 = point_read.hash_batch(hw, dk_pad)
            packs = {}
            exact_fallback = set()
            best = None  # (ht u64, wid, row, file-index, valid) arrays
            for fi, (fid, r, st) in enumerate(staged_by):
                cur["n_pad"] = st.n_pad
                maybe = point_read.probe_bloom(
                    r, h1, h2, device=self._device_cache.device)
                if maybe is not None and not maybe[:b].any():
                    metrics["bloom_skips"].increment()
                    continue
                if st.w not in packs:
                    packs[st.w] = point_read.pack_query_batch(chunk,
                                                              st.w)
                qw, ql = packs[st.w]
                model = (learned_index.model_operands(r.props.lindex,
                                                      st.n)
                         if use_model else None)
                _idx, hit, hhi, hlo, wid, miss = point_read.locate_batch(
                    st, qw, ql, read_ht.value, model)
                if model is not None:
                    metrics["learned_hits"].increment()
                    n_miss = int(miss[:b].sum())
                    if n_miss:
                        metrics["learned_fallbacks"].increment(n_miss)
                        for i in np.nonzero(miss[:b])[0]:
                            exact_fallback.add(int(i))
                ht = (hhi.astype(np.uint64) << np.uint64(32)) \
                    | hlo.astype(np.uint64)
                if best is None:
                    best = [np.zeros(b_pad, np.uint64),
                            np.zeros(b_pad, np.uint32),
                            np.zeros(b_pad, np.int64),
                            np.zeros(b_pad, np.int64),
                            np.zeros(b_pad, bool)]
                upd = hit & (~best[4] | (ht > best[0])
                             | ((ht == best[0]) & (wid > best[1])))
                best[0] = np.where(upd, ht, best[0])
                best[1] = np.where(upd, wid, best[1])
                best[2] = np.where(upd, _idx.astype(np.int64), best[2])
                best[3] = np.where(upd, fi, best[3])
                best[4] = best[4] | hit
            self._combine_device_chunk(chunk, start, read_ht, mems,
                                       staged_by, best, exact_fallback,
                                       results)

    def _combine_device_chunk(self, chunk, start, read_ht, mems,
                              staged_by, best, exact_fallback, results):
        """Merge device SST winners with host memtable probes per key —
        newest (ht, wid) wins, exactly get()'s compare."""
        live_mems = [m for m in mems if not m.empty]
        mem_hits = self._mem_probe_many(live_mems, chunk, read_ht)
        for i, k in enumerate(chunk):
            if i in exact_fallback:
                # learned-index misprediction beyond its bound: the
                # binary-search invariant caught it — resolve this key
                # exactly (correctness never rides the model)
                results[start + i] = self._get_inner(k, read_ht)
                continue
            mem_best = mem_hits[i]
            if best is not None and best[4][i]:
                ht_v = int(best[0][i])
                wid_v = int(best[1][i])
                if mem_best is None or (ht_v, wid_v) > mem_best[:2]:
                    value = self._fetch_staged_value(
                        staged_by[int(best[3][i])], int(best[2][i]))
                    results[start + i] = (
                        DocHybridTime(HybridTime(ht_v), wid_v), value)
                    continue
            results[start + i] = (
                None if mem_best is None else
                (DocHybridTime(HybridTime(mem_best[0]), mem_best[1]),
                 mem_best[2]))

    @staticmethod
    def _fetch_staged_value(entry, row: int) -> bytes:
        """Value bytes of staged entry `row` (sorted order): decode only
        the winner's block — the survivor-gather half of the batched
        read (values never live in HBM; ops/slabs.py)."""
        import numpy as np
        _fid, r, _st = entry
        offs = getattr(r, "_row_offs_pr", None)
        if offs is None:
            offs = np.concatenate(
                ([0], np.cumsum([h[2] for h in r.block_handles])))
            r._row_offs_pr = offs
        blk = int(np.searchsorted(offs, row, side="right") - 1)
        slab = r.read_block(blk)
        j = row - int(offs[blk])
        return slab.values[int(slab.value_idx[j])]

    def iter_from(self, seek_internal_key: bytes = b"",
                  check_bloom_doc: Optional[bytes] = None
                  ) -> Iterator[Tuple[bytes, bytes]]:
        """Merged (internal_key, value) stream in memcmp order (the
        MergingIterator equivalent). SSTs stream through the native read
        engine (C++ k-way merge over in-place block views) when available,
        merged lazily with the Python memtable iterators — the memtable
        never pays a repack; the full-Python heap merge remains the
        fallback and the oracle."""
        if check_bloom_doc is None and flags.get_flag("read_native"):
            from yugabyte_tpu.storage import native_read
            if native_read.available():
                # memtable snapshot BEFORE the reader set: a racing flush
                # at worst double-covers rows (deduped below), never hides
                with self._lock:
                    mems = [self.mem] + ([self._imm]
                                         if self._imm is not None else [])
                rset = self._native_rset()
                if rset is not None:
                    prefix_seek, _ = split_key_and_ht(seek_internal_key)
                    from yugabyte_tpu.storage.native_read import NativeScan
                    scan = NativeScan(rset, lower=prefix_seek, mode=2)
                    sources = [m.iter_from(seek_internal_key) for m in mems]
                    sources.append(
                        self._native_iter(scan, seek_internal_key))
                    return _dedup_ikeys(heapq.merge(*sources))
        with self._lock:
            sources = []
            sources.append(self.mem.iter_from(seek_internal_key))
            if self._imm is not None:
                sources.append(self._imm.iter_from(seek_internal_key))
            readers = list(self._readers.values())
        for r in readers:
            if check_bloom_doc is not None and not r.may_contain_doc(check_bloom_doc):
                continue
            sources.append(_sst_iter_from(r, seek_internal_key))
        return heapq.merge(*sources)

    @staticmethod
    def _native_iter(scan, seek_internal_key: bytes
                     ) -> Iterator[Tuple[bytes, bytes]]:
        """Adapt a mode-2 NativeScan to the iter_from contract. The native
        seek is by key PREFIX (any version); when the seek carried an HT
        suffix, drop the leading newer-version entries it excludes."""
        skipping = bool(seek_internal_key)
        for batch in scan.batches():
            koffs, voffs = batch.key_offs, batch.val_offs
            keys, vals = batch.keys, batch.vals
            for i in range(batch.n):
                ikey = keys[koffs[i]: koffs[i + 1]].tobytes()
                if skipping:
                    if ikey < seek_internal_key:
                        continue
                    skipping = False
                yield ikey, vals[voffs[i]: voffs[i + 1]].tobytes()

    def scan_visible(self, read_ht_value: int,
                     lower_key: Optional[bytes] = None,
                     upper_key: Optional[bytes] = None):
        """TPU scan path: yield (key_prefix, value_bytes, ht_value) of every
        entry visible at read_ht in [lower_key, upper_key), in key order.

        One fused device program resolves merge + MVCC visibility + range
        filter for the whole range (ops/scan.py), instead of the per-step
        Python heap merge of iter_from. SST key columns come from the HBM
        slab cache (write-through on miss) — a RESIDENT file is never
        block-decoded to stage the filter: the kernel runs over the
        cached matrix and only the blocks holding surviving entries are
        decoded for their keys/values (ops/scan.ResidentSource). Input
        SSTs are PINNED for the scan's lifetime so a concurrent
        compaction cannot delete them (the reference's Version
        refcounting, ref: db/version_set.cc).
        """
        from yugabyte_tpu.ops.scan import (ResidentSource, SlabSource,
                                           visible_entries_sources)
        import time as _time
        t0 = _time.monotonic()
        with self._lock:
            slabs = [self.mem.to_slab()]
            if self._imm is not None:
                slabs.append(self._imm.to_slab())
            readers = list(self._readers.items())
            for fid, _ in readers:
                self._pins[fid] = self._pins.get(fid, 0) + 1
        try:
            sources = [SlabSource(sl) for sl in slabs]
            for fid, r in readers:
                st = (self._device_cache.get(fid)
                      if self._device_cache is not None else None)
                if st is not None and not r.props.has_deep:
                    # resident fast path: zero host block decode to stage
                    sources.append(ResidentSource(r, st))
                    continue
                try:
                    sl = r.read_all()
                except StatusError as e:
                    # corrupt block under a scan: park + fail retryably
                    # (the client walks replicas), never a raw Corruption
                    self._route_read_corruption(e)
                    raise
                if self._device_cache is not None and not r.props.has_deep:
                    st = self._device_cache.stage(fid, sl, for_read=True)
                    sources.append(SlabSource(sl, st))
                else:
                    sources.append(SlabSource(sl))
            try:
                yield from visible_entries_sources(
                    sources, read_ht_value, lower_key, upper_key,
                    device=self.opts.device)
            except StatusError as e:
                # a resident source decodes survivor blocks lazily — a
                # corrupt block surfacing mid-stream takes the same
                # containment path as the eager decode above
                self._route_read_corruption(e)
                raise
        finally:
            _storage_metrics()[1].increment(
                (_time.monotonic() - t0) * 1e3)
            with self._lock:
                for fid, _ in readers:
                    self._pins[fid] -= 1
                    if not self._pins[fid]:
                        del self._pins[fid]
                self._purge_obsolete_unlocked()

    # ----------------------------------------------------- query pushdown
    def _pushdown_sources(self, spec):
        """Build the fused-scan source list with pins held + value words
        staged (ROADMAP item 5). Returns (sources, readers) — the caller
        owns unpinning via _release_scan_pins. Raises
        PushdownUnsupported("deep") on deep-document files (the kernels
        are depth-2 only) so callers fall back host-side, counted."""
        from yugabyte_tpu.docdb.scan_spec import PushdownUnsupported
        from yugabyte_tpu.ops.scan import (ResidentSource, SlabSource,
                                           pack_vals, pushdown_metrics)
        with self._lock:
            slabs = [self.mem.to_slab()]
            if self._imm is not None:
                slabs.append(self._imm.to_slab())
            readers = list(self._readers.items())
            for fid, _ in readers:
                self._pins[fid] = self._pins.get(fid, 0) + 1
        try:
            sources = [SlabSource(sl) for sl in slabs]
            for fid, r in readers:
                if r.props.has_deep:
                    raise PushdownUnsupported("deep")
                st = (self._device_cache.get(fid)
                      if self._device_cache is not None else None)
                if st is None:
                    sl = self._read_all_contained(r)
                    if self._device_cache is not None:
                        st = self._device_cache.stage(
                            fid, sl, for_read=True,
                            include_vals=spec.needs_vals)
                        sources.append(ResidentSource(r, st))
                    else:
                        sources.append(SlabSource(sl, sorted_source=True))
                    continue
                if spec.needs_vals and st.vals_dev is None:
                    # resident cols without value words: decode once,
                    # attach, and every later pushdown scan is resident
                    import jax
                    import jax.numpy as jnp
                    sl = self._read_all_contained(r)
                    packed = pack_vals(sl, st.n_pad)
                    dev = self._device_cache.device
                    vals_dev = (jax.device_put(packed, dev)
                                if dev is not None
                                else jnp.asarray(packed))
                    self._device_cache.attach_vals(fid, vals_dev)
                    pushdown_metrics()["vals_staged"].increment()
                sources.append(ResidentSource(r, st))
            return sources, readers
        except BaseException:
            self._release_scan_pins(readers)
            raise

    def _read_all_contained(self, r):
        try:
            return r.read_all()
        except StatusError as e:
            self._route_read_corruption(e)
            raise

    def _release_scan_pins(self, readers) -> None:
        with self._lock:
            for fid, _ in readers:
                self._pins[fid] -= 1
                if not self._pins[fid]:
                    del self._pins[fid]
            self._purge_obsolete_unlocked()

    def scan_filtered(self, read_ht_value: int, spec,
                      lower_key: Optional[bytes] = None,
                      upper_key: Optional[bytes] = None):
        """Fused filtered scan: yields the visible entries of exactly
        the rows satisfying spec.predicates, resolved in one device
        dispatch over the resident slab matrices. The dispatch runs
        EAGERLY — device faults surface here (as PushdownUnsupported,
        bucket quarantined) with zero rows emitted and zero pins leaked,
        so the caller can serve the same query through the host path."""
        from yugabyte_tpu.ops.scan import (ResidentSource,
                                           filtered_entries_sources,
                                           pushdown_metrics)
        sources, readers = self._pushdown_sources(spec)
        try:
            it = filtered_entries_sources(
                sources, read_ht_value, spec, lower_key, upper_key,
                device=self.opts.device)
        except BaseException:
            self._release_scan_pins(readers)
            raise

        def entries():
            try:
                yield from it
            except StatusError as e:
                # corrupt winner block mid-stream: same containment as
                # the plain scan path (park + retryable to the client)
                self._route_read_corruption(e)
                raise
            finally:
                blocks = sum(s.decoded_blocks for s in sources
                             if isinstance(s, ResidentSource))
                pushdown_metrics()["blocks"].increment(max(blocks, 0))
                self._release_scan_pins(readers)

        return entries()

    def scan_aggregate(self, read_ht_value: int, spec,
                       lower_key: Optional[bytes] = None,
                       upper_key: Optional[bytes] = None) -> dict:
        """Fused aggregating scan: one dispatch returns the aggregate
        partial for this DB's whole source set ({"rows", "cols"}), with
        exact MVCC visibility across memtables and SSTs. Scalars only —
        host memory is touched once per RESULT, not once per row."""
        from yugabyte_tpu.ops.scan import aggregate_sources
        sources, readers = self._pushdown_sources(spec)
        try:
            return aggregate_sources(sources, read_ht_value, spec,
                                     lower_key, upper_key,
                                     device=self.opts.device)
        finally:
            self._release_scan_pins(readers)

    # ----------------------------------------------------------------- flush
    def flush(self) -> Optional[int]:
        """Memtable -> L0 SST (ref: db/flush_job.cc).

        The lock is held only to swap the memtable and to install the result;
        slab packing + SST write + fsync run unlocked while reads serve from
        the immutable memtable (self._imm).
        """
        with self._lock:
            if self._imm is not None:
                return None  # a flush is already in progress
            if self._bg_error is not None:
                return None  # parked: retry_background_work re-drives
            if self.mem.empty:
                return None
            self._imm, self.mem = self.mem, new_memtable()
            imm = self._imm
            last_op = getattr(self, "_last_op_id", (0, 0))
        fid = path = None
        try:
            if self.pre_flush_hook is not None:
                self.pre_flush_hook()
            fid = self.versions.new_file_id()
            path = os.path.join(self.db_dir, f"{fid:06d}.sst")
            with self._lock:
                self._writing.add(path)
            slab = None
            from yugabyte_tpu.storage import native_engine
            from yugabyte_tpu.utils.env import get_env
            if native_engine.available() and not get_env().encrypted:
                # native flush encoder: block encode + bloom + doc-key
                # parsing in C++ (the write-path hot loop, ref:
                # db/flush_job.cc WriteLevel0Table), with run-cache
                # write-through so the first compaction over this output
                # skips read+decode. Device staging (below) still needs
                # the slab form — a second memtable walk, much cheaper
                # than the Python block encoder it replaces.
                packed = imm.to_packed()
                frontier = Frontier(op_id_min=last_op, op_id_max=last_op,
                                    history_cutoff=0)
                from yugabyte_tpu.storage.sst import write_sst_from_packed
                props = write_sst_from_packed(
                    path, *packed, frontier=frontier,
                    block_entries=self.opts.block_entries,
                    run_cache=self._run_cache, file_id=fid)
                n_flushed = len(packed[1]) - 1
                if self._device_cache is not None:
                    slab = imm.to_slab()
            else:
                slab = imm.to_slab()
                ht = slab.ht_hi.astype("u8") << 32 | slab.ht_lo
                frontier = Frontier(op_id_min=last_op, op_id_max=last_op,
                                    ht_min=int(ht.min()) if slab.n else 0,
                                    ht_max=int(ht.max()) if slab.n else 0,
                                    history_cutoff=0)
                props = SSTWriter(path, block_entries=self.opts.block_entries).write(slab, frontier)
                n_flushed = slab.n
            from yugabyte_tpu.utils import sync_point
            sync_point.hit("db.flush:before_manifest")
            if self._device_cache is not None and slab is not None:
                self._device_cache.stage(fid, slab)  # write-through to HBM
            with self._lock:
                self.versions.add_file(fid, path, props)
                self.versions.set_flushed_frontier(frontier)
                self._readers[fid] = SSTReader(path, self.opts.block_cache)
                self._imm = None
                self._rset = None  # native snapshot is stale
                self._rset_gen += 1
                self._mem_run_cache = None
            self.compaction_stats.record_flush(
                props.data_size + props.base_size, n_flushed)
            TRACE("flushed %d entries to %s", n_flushed, path)
        except BaseException as e:
            with self._lock:
                # restore un-flushed entries into the live memtable
                for k, v in imm.iter_from():
                    prefix, dht = split_key_and_ht(k)
                    self.mem.add(prefix, dht, v)
                self._imm = None
                # partial outputs of the aborted flush — but never a file
                # the version set already adopted (an error between the
                # manifest add and the frontier edit leaves it live)
                installed = fid is not None and fid in self.versions.files
            if path is not None and not installed:
                _delete_sst_files(path)
                if self._device_cache is not None and fid is not None:
                    self._device_cache.drop(fid)
            from yugabyte_tpu.utils.status import StatusError
            if isinstance(e, (OSError, StatusError)):
                # Contained: version set untouched (or still consistent),
                # no rows lost (memtable restored). Park read-only; the
                # maintenance manager retries with capped backoff.
                self._set_background_error("flush", e)
                return None
            raise
        finally:
            if path is not None:
                with self._lock:
                    self._writing.discard(path)
        if self.opts.auto_compact:
            self.maybe_schedule_compaction()
        return fid

    # ------------------------------------------------------------ compaction
    def maybe_schedule_compaction(self) -> bool:
        """(ref: DBImpl::MaybeScheduleFlushOrCompaction db_impl.cc:2127)."""
        with self._lock:
            if self._compacting or self._closed or \
                    self._bg_error is not None:
                return False
            pick = compaction_mod.pick_universal(self.versions.live_files())
            if pick is None:
                return False
            self._compacting = True
            for fm in pick.inputs:
                fm.being_compacted = True
        if self.opts.compaction_pool is not None:
            self.opts.compaction_pool.submit(lambda: self._run_compaction(pick),
                                             priority=0)
        else:
            self._run_compaction(pick)
        return True

    def _run_compaction(self, pick) -> None:
        try:
            self._run_compaction_inner(pick)
        except BaseException as e:
            from yugabyte_tpu.utils.cancellation import OperationCancelled
            from yugabyte_tpu.utils.status import StatusError
            if isinstance(e, OperationCancelled):
                # CLEAN abort (shutdown / tablet-FAILED): nothing was
                # installed and the job unwound its own partials; sweep
                # any stragglers but do NOT park the DB — this is not a
                # storage fault.
                with self._lock:
                    self._sweep_orphan_outputs_unlocked()
                TRACE("db %s: compaction aborted: %s", self.db_dir, e)
                return
            if not isinstance(e, (OSError, StatusError)):
                raise
            # Contained like a failed flush: the version set still points
            # at the inputs (nothing installed), partial outputs are swept,
            # and the DB parks read-only for the backoff retry. A
            # CORRUPTION status (corrupt input block tripped the decode —
            # Python or native shell) parks STICKY instead: retrying into
            # the same bad bytes can never succeed; the replica must be
            # rebuilt from a healthy peer.
            from yugabyte_tpu.utils.status import Code
            with self._lock:
                self._sweep_orphan_outputs_unlocked()
            self._set_background_error(
                "compaction", e,
                corruption=isinstance(e, StatusError)
                and e.status.code == Code.CORRUPTION)

    def _run_compaction_inner(self, pick) -> None:
        try:
            inputs = [self._readers[fm.file_id] for fm in pick.inputs]
            cutoff = self.opts.retention_policy()
            result = self._dispatch_compaction(pick, inputs, cutoff)
            from yugabyte_tpu.utils import sync_point
            sync_point.hit("db.compaction:before_install")
            with self._lock:
                removed = [fm.file_id for fm in pick.inputs]
                self.versions.install_compaction(
                    removed, [(fid, p, props) for fid, p, props in result.outputs])
                self._rset = None  # native snapshot is stale; removed
                self._rset_gen += 1
                # native readers are dropped from the dict below and freed
                # by refcount once in-flight scans release their snapshot
                for fid, path, props in result.outputs:
                    self._readers[fid] = SSTReader(path, self.opts.block_cache)
                for fid in removed:
                    self._native_readers.pop(fid, None)
                    r = self._readers.pop(fid, None)
                    if r:
                        if self._pins.get(fid):
                            # an active scan still reads this SST: defer the
                            # close+delete until its pin drops
                            self._obsolete[fid] = r
                        else:
                            r.close()
                            _delete_sst_files(r.base_path)
                    if self._device_cache is not None:
                        self._device_cache.drop(fid)
                    if self._run_cache is not None:
                        self._run_cache.drop(fid)
            self.compaction_stats.record_compaction(
                bytes_read=sum(fm.total_size for fm in pick.inputs),
                bytes_written=sum(p.data_size + p.base_size
                                  for _fid, _path, p in result.outputs),
                files_in=len(pick.inputs), files_out=len(result.outputs),
                rows_in=result.rows_in, rows_out=result.rows_out,
                tombstones_written=result.tombstones_written)
            TRACE("compaction: %d files -> %d rows (%d in)",
                  len(pick.inputs), result.rows_out, result.rows_in)
        finally:
            with self._lock:
                self._compacting = False
                # On failure the inputs stay live: make them pickable again.
                for fm in pick.inputs:
                    fm.being_compacted = False
                # Reap deferred readers whose pinning scans finished while
                # this compaction ran (scans also purge on exit; this covers
                # the case where no further scan ever happens).
                self._purge_obsolete_unlocked()
        # cascade if still over trigger
        if self.opts.auto_compact:
            self.maybe_schedule_compaction()

    def _dispatch_compaction(self, pick, inputs, cutoff):
        """Route one picked compaction: through the mesh-sharded
        multi-tablet pool when this server has one AND the job would take
        the device path anyway (the same measured offload decision the
        inline path makes — the pool is a scheduling win, never a routing
        override), else the inline run_compaction_job."""
        pool = self.opts.mesh_pool
        if pool is not None and self.opts.device not in (None, "native"):
            est = sum(r.props.n_entries for r in inputs)
            has_deep = any(r.props.has_deep for r in inputs)
            board = self.opts.offload_policy
            cached = bool(self._device_cache is not None and all(
                self._device_cache.contains(fm.file_id)
                for fm in pick.inputs))
            use = True
            if board is not None:
                from yugabyte_tpu.ops.run_merge import packed_run_ns
                from yugabyte_tpu.storage.offload_policy import bucket_key
                qkey = bucket_key(packed_run_ns(
                    [r.props.n_entries for r in inputs
                     if r.props.n_entries]))
                # probe=False: this thread only SUBMITS — the pool
                # worker that dispatches claims any probe slot itself
                use = board.use_device("run_merge_fused", qkey,
                                       est_rows=est, cached=cached,
                                       probe=False)
            if not has_deep and use:
                handle = pool.submit_compaction(
                    self.db_dir, inputs=inputs, out_dir=self.db_dir,
                    new_file_id=self.versions.new_file_id,
                    history_cutoff_ht=cutoff, is_major=pick.is_major,
                    block_entries=self.opts.block_entries,
                    input_ids=[fm.file_id for fm in pick.inputs],
                    device_cache=self._device_cache, est_rows=est,
                    cancel=self._cancel)
                return handle.result()
        return compaction_mod.run_compaction_job(
            inputs, self.db_dir, self.versions.new_file_id, cutoff,
            pick.is_major, device=self.opts.device,
            block_entries=self.opts.block_entries,
            device_cache=self._device_cache,
            input_ids=[fm.file_id for fm in pick.inputs],
            mesh=self.opts.mesh,
            offload_policy=self.opts.offload_policy,
            run_cache=self._run_cache,
            cancel=self._cancel)

    def compact_all(self) -> None:
        """Force a full (major) compaction of all live files."""
        with self._lock:
            files = [f for f in self.versions.live_files() if not f.being_compacted]
            if len(files) < 2:
                return
            for fm in files:
                fm.being_compacted = True
            pick = compaction_mod.CompactionPick(files, is_major=True)
            self._compacting = True
        self._run_compaction(pick)

    def _purge_obsolete_unlocked(self) -> None:
        for fid in [f for f in self._obsolete if not self._pins.get(f)]:
            r = self._obsolete.pop(fid)
            r.close()
            _delete_sst_files(r.base_path)

    # ------------------------------------------------------------------ scrub
    def scrub(self, limiter=None, cancel=None) -> dict:
        """At-rest integrity scrub: deep-verify every live SST (block
        CRCs, footer, index/bloom consistency — storage/integrity.py) at
        a throttled byte rate. Files are PINNED while verified so a
        concurrent compaction cannot delete them mid-read. A corrupt
        file is quarantined (renamed ``*.corrupt``) and the DB parks
        with a STICKY Corruption background error — the owner tablet
        goes FAILED (``failed_corrupt``) and must be rebuilt from a
        healthy peer; in-place retry is refused."""
        from yugabyte_tpu.storage import integrity
        from yugabyte_tpu.utils.status import Status
        with self._lock:
            targets = [(fid, r.base_path)
                       for fid, r in self._readers.items()]
            for fid, _ in targets:
                self._pins[fid] = self._pins.get(fid, 0) + 1
        report = {"files": 0, "blocks": 0, "entries": 0, "bytes": 0,
                  "corrupt": []}
        try:
            for fid, base_path in targets:
                if cancel is not None:
                    cancel.check()
                rep = integrity.verify_sst(base_path, limiter=limiter,
                                           cancel=cancel)
                report["files"] += 1
                report["blocks"] += rep.n_blocks
                report["entries"] += rep.n_entries
                report["bytes"] += rep.bytes_verified
                if rep.errors:
                    report["corrupt"].append(
                        {"path": base_path, "errors": rep.errors[:4]})
                    integrity.quarantine_sst(base_path,
                                             reason=rep.errors[0])
                    self._set_background_error(
                        "scrub",
                        StatusError(Status.Corruption(
                            f"{base_path}: {rep.errors[0]}")),
                        corruption=True)
        finally:
            with self._lock:
                for fid, _ in targets:
                    self._pins[fid] -= 1
                    if not self._pins[fid]:
                        del self._pins[fid]
                self._purge_obsolete_unlocked()
        integrity.record_scrub(report["files"], report["blocks"],
                               report["bytes"], len(report["corrupt"]))
        return report

    # ------------------------------------------------------------ checkpoint
    def checkpoint(self, out_dir: str) -> None:
        """Hard-link snapshot (ref: utilities/checkpoint/checkpoint.cc:56)."""
        os.makedirs(out_dir, exist_ok=True)
        with self._lock:
            for fm in self.versions.live_files():
                for p in (fm.path, data_file_name(fm.path)):
                    os.link(p, os.path.join(out_dir, os.path.basename(p)))
            import shutil
            if os.path.exists(self.versions.manifest_path):
                shutil.copy(self.versions.manifest_path,
                            os.path.join(out_dir, "MANIFEST"))

    def close(self) -> None:
        # trip the cancellation seam FIRST: an in-flight pipelined
        # compaction aborts at its next stage boundary instead of writing
        # into a directory whose readers we are about to close
        self._cancel.cancel("db closed")
        with self._lock:
            self._closed = True
            # native handles free via refcount (in-flight scans may still
            # hold the snapshot)
            self._native_readers = {}
            self._rset = None
            self._rset_gen += 1
            self._mem_run_cache = None
            self._purge_obsolete_unlocked()
            for r in self._obsolete.values():
                r.close()  # still pinned: close the handle, leave the files
            self._obsolete.clear()
            for r in self._readers.values():
                r.close()
            self._readers.clear()
            if self._device_cache is not None and \
                    hasattr(self._device_cache, "drop_all"):
                self._device_cache.drop_all()  # free this DB's HBM residency
            if self._run_cache is not None:
                self._run_cache.drop_all()

    @property
    def n_live_files(self) -> int:
        return len(self.versions.files)


def _dedup_ikeys(stream: Iterator[Tuple[bytes, bytes]]
                 ) -> Iterator[Tuple[bytes, bytes]]:
    """Suppress adjacent duplicate internal keys: a flush racing the
    memtable snapshot can surface one row from both the memtable and the
    fresh SST; legitimate data never repeats a full internal key."""
    prev = None
    for kv in stream:
        if kv[0] == prev:
            continue
        prev = kv[0]
        yield kv


def _sst_iter_from(reader: SSTReader, seek: bytes) -> Iterator[Tuple[bytes, bytes]]:
    """Merged-stream source over one SST from `seek` (internal-key order).

    The first block is entered by BINARY SEARCH on the reconstructed
    internal keys — the old linear skip from the block start cost ~half a
    block (~2K entry decodes) per point read and dominated YCSB-C wall
    time (ref: the reference's block restart-point binary seek,
    rocksdb/table/block.cc Seek)."""
    prefix_seek, _ = split_key_and_ht(seek)
    b = reader.seek_block(prefix_seek if prefix_seek else seek)
    # Search phase: binary-search each block until one holds an entry
    # >= seek. The block index is on key PREFIXES while seek carries the
    # HT suffix, so a version chain spilling across blocks can leave the
    # first (or several) candidate blocks entirely below seek — stopping
    # the search after one block would emit too-new versions unfiltered.
    while b < reader.n_blocks:
        slab = reader.read_block(b)
        raw = slab.key_words.astype(">u4").tobytes()
        stride = slab.width_words * 4

        def ikey(i: int) -> bytes:
            kp = raw[i * stride: i * stride + int(slab.key_len[i])]
            return make_internal_key(kp, slab.doc_ht(i))

        lo, hi = 0, slab.n
        while lo < hi:
            mid = (lo + hi) // 2
            if ikey(mid) < seek:
                lo = mid + 1
            else:
                hi = mid
        b += 1
        if lo < slab.n:
            for i in range(lo, slab.n):
                yield ikey(i), slab.values[int(slab.value_idx[i])]
            break
        # whole block < seek: search the next one
    # Stream phase: every later block is entirely >= seek — reuse the
    # reader's own decode loop rather than duplicating it here.
    for kp, dht, value, _fl in reader.iter_entries(b):
        yield make_internal_key(kp, dht), value


def _delete_sst_files(base_path: str) -> None:
    for p in (base_path, data_file_name(base_path)):
        try:
            os.remove(p)
        except FileNotFoundError:  # yblint: contained(idempotent delete — both halves may already be gone)
            pass
