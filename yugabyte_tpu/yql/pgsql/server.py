"""PostgreSQL wire-protocol (v3) server for the YSQL layer.

Any client speaking the PG v3 simple-query protocol (psql, drivers in
simple-query mode) can connect: startup handshake (incl. SSLRequest
refusal), AuthenticationOk, ParameterStatus, simple 'Q' queries answered
with RowDescription/DataRow/CommandComplete, ErrorResponse with SQLSTATE,
and transaction-aware ReadyForQuery status. Replaces the role of the
reference's forked-postgres frontend process (ref: yql/pgwrapper/
pg_wrapper.cc launching postgres; the protocol itself is implemented by
the PG11 fork there — here it is a native part of the framework).

Message formats follow the protocol spec exactly; see each _send_* helper.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Tuple

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.transaction import TransactionManager
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.yql.pgsql.executor import PgError, PgResult, PgSession

PROTOCOL_V3 = 196608          # 3.0
SSL_REQUEST_CODE = 80877103
CANCEL_REQUEST_CODE = 80877102
GSS_REQUEST_CODE = 80877104


def _cstr(s: str) -> bytes:
    return s.encode("utf-8") + b"\x00"


def _encode_text(v: object) -> Optional[bytes]:
    """PG text-format value encoding."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return b"\\x" + v.hex().encode()
    if isinstance(v, float):
        return repr(v).encode()
    return str(v).encode("utf-8")


class _Conn:
    def __init__(self, sock: socket.socket, server: "PgServer"):
        self.sock = sock
        self.server = server
        self.session: Optional[PgSession] = None

    # ------------------------------------------------------------- framing
    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client disconnected")
            buf += chunk
        return buf

    def _send(self, type_byte: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(type_byte + struct.pack(">I", len(payload) + 4)
                          + payload)

    # ------------------------------------------------------------- startup
    def handshake(self) -> bool:
        while True:
            (length,) = struct.unpack(">I", self._recv_exact(4))
            payload = self._recv_exact(length - 4)
            (code,) = struct.unpack_from(">I", payload, 0)
            if code == SSL_REQUEST_CODE or code == GSS_REQUEST_CODE:
                self.sock.sendall(b"N")  # SSL/GSS not supported; retry plain
                continue
            if code == CANCEL_REQUEST_CODE:
                return False  # cancel keys are not tracked; just close
            if code != PROTOCOL_V3:
                self._send_error("08P01",
                                 f"unsupported protocol {code >> 16}."
                                 f"{code & 0xFFFF}")
                return False
            params = {}
            parts = payload[4:].split(b"\x00")
            for k, v in zip(parts[::2], parts[1::2]):
                if k:
                    params[k.decode()] = v.decode()
            database = params.get("database") or params.get("user") \
                or "postgres"
            try:
                self.session = PgSession(self.server.client,
                                         self.server.txn_manager, database)
            except PgError as e:
                self._send_error(e.sqlstate, e.status.message)
                return False
            except StatusError as e:
                self._send_error("XX000", e.status.message)
                return False
            # AuthenticationOk
            self._send(b"R", struct.pack(">I", 0))
            for k, v in (("server_version", "11.2 (yugabyte-tpu)"),
                         ("server_encoding", "UTF8"),
                         ("client_encoding", "UTF8"),
                         ("DateStyle", "ISO, MDY"),
                         ("integer_datetimes", "on"),
                         ("standard_conforming_strings", "on")):
                self._send(b"S", _cstr(k) + _cstr(v))
            # BackendKeyData (pid, secret) — cancel is accepted-and-ignored
            self._send(b"K", struct.pack(">II", threading.get_ident()
                                         & 0x7FFFFFFF, 0))
            self._send_ready()
            return True

    # ------------------------------------------------------------ messages
    def _send_ready(self) -> None:
        status = self.session.transaction_status() if self.session else "I"
        self._send(b"Z", status.encode())

    def _send_error(self, sqlstate: str, message: str) -> None:
        fields = (b"S" + _cstr("ERROR") + b"V" + _cstr("ERROR")
                  + b"C" + _cstr(sqlstate) + b"M" + _cstr(message)
                  + b"\x00")
        self._send(b"E", fields)

    def _send_result(self, r: PgResult) -> None:
        if r.columns is not None:
            desc = struct.pack(">H", len(r.columns))
            for name, oid in r.columns:
                desc += (_cstr(name) + struct.pack(">IHIhih", 0, 0, oid,
                                                   -1, -1, 0))
            self._send(b"T", desc)
            for row in r.rows:
                body = struct.pack(">H", len(row))
                for v in row:
                    enc = _encode_text(v)
                    if enc is None:
                        body += struct.pack(">i", -1)
                    else:
                        body += struct.pack(">I", len(enc)) + enc
                self._send(b"D", body)
        self._send(b"C", _cstr(r.tag))

    # ---------------------------------------------------------------- loop
    def run(self) -> None:
        try:
            if not self.handshake():
                return
            while True:
                t = self._recv_exact(1)
                (length,) = struct.unpack(">I", self._recv_exact(4))
                payload = self._recv_exact(length - 4)
                if t == b"X":
                    return
                if t == b"Q":
                    self._ext_error_sent = False
                    self._simple_query(payload[:-1].decode("utf-8"))
                elif t in (b"P", b"B", b"D", b"E", b"C", b"F"):
                    # extended protocol: error ONCE, then discard every
                    # message until the client's Sync (per-protocol error
                    # recovery), so the driver's accounting stays in step
                    if not getattr(self, "_ext_error_sent", False):
                        self._send_error(
                            "0A000", "extended query protocol not "
                            "supported; use simple query mode")
                        self._ext_error_sent = True
                elif t == b"S":  # Sync: ends an extended-protocol cycle
                    self._ext_error_sent = False
                    self._send_ready()
                elif t == b"H":  # Flush
                    pass
                else:
                    self._send_error("08P01",
                                     f"unknown message type {t!r}")
                    self._send_ready()
        except (ConnectionError, OSError):
            pass
        finally:
            if self.session is not None:
                self.session.close()
            try:
                self.sock.close()
            except OSError:
                pass

    def _simple_query(self, sql: str) -> None:
        if not sql.strip():
            self._send(b"I")  # EmptyQueryResponse
            self._send_ready()
            return
        try:
            for result in self.session.execute(sql):
                self._send_result(result)
        except PgError as e:
            self._send_error(e.sqlstate, e.status.message)
        except StatusError as e:
            self._send_error("XX000", e.status.message)
        self._send_ready()


class PgServer:
    """Listens for PG-protocol connections, thread per connection (the
    reference runs one postgres backend process per connection;
    ref pg_wrapper.cc)."""

    def __init__(self, client: YBClient, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.txn_manager = TransactionManager(client)
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._shutdown = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="pg-accept")
        self._accept_thread.start()
        TRACE("pg server listening on %s:%d", self.host, self.port)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=_Conn(sock, self).run, daemon=True,
                             name="pg-conn").start()

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
