"""ClusterLoadBalancer: replica repair + balancing.

Capability parity with the reference (ref: src/yb/master/cluster_balance.h
:63-78 — the balancer walks the tablet list, finds under-replicated /
misplaced replicas, and drives one bounded batch of moves per pass:
remote-bootstrap the new replica, ChangeConfig ADD, ChangeConfig REMOVE the
dead one; catalog state follows the consensus config reported by tablet
leaders, not the other way around).

Safety rails mirrored from the reference: a grace period before a silent
tserver is declared dead, a cap on concurrent moves per pass, and an
initial delay after master leadership change (heartbeats must repopulate
the TS registry before anything is judged dead).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("load_balancer_dead_grace_ms", 5000,
                  "how long a tserver must be silent before its replicas "
                  "are moved (ref follower_unavailable_considered_failed_sec)")
flags.define_flag("load_balancer_max_moves_per_pass", 2,
                  "bound on replica moves started per balancer pass "
                  "(ref load_balancer_max_concurrent_moves)")


class ClusterLoadBalancer:
    def __init__(self, catalog, messenger):
        self.catalog = catalog
        self.messenger = messenger
        self._leader_since: Optional[float] = None

    # ---------------------------------------------------------------- pass
    def run_pass(self) -> int:
        """One balancing pass on the master leader; returns moves started."""
        cm = self.catalog
        now = time.monotonic()
        if self._leader_since is None:
            self._leader_since = now
        grace_s = flags.get_flag("load_balancer_dead_grace_ms") / 1000.0
        if now - self._leader_since < 2 * grace_s:
            return 0  # let heartbeats repopulate the registry first
        live = {d.server_id: d for d in cm.ts_manager.live_descriptors()}
        addr_map = cm.ts_manager.addr_map()
        moves = 0
        max_moves = flags.get_flag("load_balancer_max_moves_per_pass")
        tablets_snap, leaders_snap = cm.balancer_snapshot()
        for tablet_id, tm in tablets_snap.items():
            if moves >= max_moves:
                break
            leader = leaders_snap.get(tablet_id)
            # Corruption-reported replicas (scrub / read-path CRC /
            # digest divergence) are rebuilt IN PLACE from the leader:
            # the server is alive and its disk works — only this
            # replica's data is bad — so no spare is needed (which also
            # makes repair possible when RF == cluster size).
            corrupt = [s for s in tm["replicas"]
                       if s in live
                       and self._reported_corrupt(s, tablet_id)]
            if corrupt:
                if leader is None or leader[0] not in live \
                        or leader[0] == corrupt[0]:
                    continue  # need a healthy live leader as the source
                if self._rebuild_replica(tablet_id,
                                         addr_map[leader[0]],
                                         corrupt[0], addr_map):
                    moves += 1
                continue
            # A replica is repair-worthy when its server has gone silent
            # past the grace period OR the server itself reports the
            # replica FAILED (background storage error) — an explicit
            # report needs no grace (ref: the reference treats
            # TABLET_DATA_TOMBSTONED/failed replicas as under-replication).
            dead = [s for s in tm["replicas"]
                    if self._dead_for(s) > grace_s
                    or self._reported_failed(s, tablet_id)]
            if not dead:
                continue
            if leader is None or leader[0] not in live:
                continue  # no live leader to drive the change through
            spare = self._pick_spare(live, tm["replicas"])
            if spare is None:
                continue
            if self._move_replica(tablet_id, addr_map[leader[0]],
                                  dead[0], spare):
                moves += 1
        return moves

    def on_leadership_change(self) -> None:
        self._leader_since = None

    def _reported_failed(self, server_id: str, tablet_id: str) -> bool:
        desc = self.catalog.ts_manager.get(server_id)
        return desc is not None and tablet_id in desc.failed_tablets

    def _reported_corrupt(self, server_id: str, tablet_id: str) -> bool:
        desc = self.catalog.ts_manager.get(server_id)
        return desc is not None and tablet_id in desc.corrupt_tablets

    def _dead_for(self, server_id: str) -> float:
        desc = self.catalog.ts_manager.get(server_id)
        if desc is None:
            # Unknown since this master became leader: counts as dead only
            # after the initial-delay gate above has passed.
            return float("inf")
        return time.monotonic() - desc.last_heartbeat

    def _pick_spare(self, live: Dict[str, object],
                    replicas: List[str]) -> Optional[str]:
        candidates = [d for sid, d in live.items() if sid not in replicas]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda d: (d.num_tablets, d.server_id)).server_id

    # ------------------------------------------------------------- rebuild
    def _rebuild_replica(self, tablet_id: str, leader_addr: str,
                         server_id: str, addr_map) -> bool:
        """In-place repair of a corruption-failed replica: tell ITS OWN
        server to remote-bootstrap the tablet from the healthy leader.
        The tserver tears the corrupt copy down first (the sticky
        Corruption error guarantees nothing else will un-park it); the
        Raft config is unchanged, so a crash mid-rebuild is simply
        retried by a later pass."""
        addr = addr_map.get(server_id)
        if addr is None:
            return False
        TRACE("lb: rebuilding corrupt replica %s of %s in place from %s",
              server_id, tablet_id, leader_addr)
        try:
            self.messenger.call(addr, "tserver", "start_remote_bootstrap",
                                timeout_s=60.0, tablet_id=tablet_id,
                                source_addr=leader_addr)
        except StatusError as e:
            TRACE("lb: rebuild of %s on %s failed (retried next pass): %s",
                  tablet_id, server_id, e)
            return False
        return True

    # ---------------------------------------------------------------- move
    def _move_replica(self, tablet_id: str, leader_addr: str,
                      dead_server: str, new_server: str) -> bool:
        """dead -> new replica move. Every step is idempotent, so a crash
        mid-move is finished by a later pass (consensus config reported by
        the leader resyncs the catalog)."""
        cm = self.catalog
        addr_map = cm.ts_manager.addr_map()
        new_addr = addr_map.get(new_server)
        if new_addr is None:
            return False
        TRACE("lb: moving %s replica %s -> %s", tablet_id, dead_server,
              new_server)
        try:
            self.messenger.call(new_addr, "tserver",
                                "start_remote_bootstrap", timeout_s=60.0,
                                tablet_id=tablet_id,
                                source_addr=leader_addr)
            self.messenger.call(leader_addr, "tserver", "change_config",
                                timeout_s=30.0, tablet_id=tablet_id,
                                add=[new_server])
            self.messenger.call(leader_addr, "tserver", "change_config",
                                timeout_s=30.0, tablet_id=tablet_id,
                                remove=[dead_server])
        except StatusError as e:
            TRACE("lb: move of %s failed midway (retried next pass): %s",
                  tablet_id, e)
            return False
        cm.update_tablet_replicas(
            tablet_id,
            [new_server if s == dead_server else s
             for s in cm.tablet_replicas(tablet_id)])
        return True
