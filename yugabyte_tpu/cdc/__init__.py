"""CDC + xCluster async replication (ref: ent/src/yb/cdc/,
ent/src/yb/tserver/cdc_poller.cc)."""
