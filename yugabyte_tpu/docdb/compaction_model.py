"""Reference semantic model of compaction MVCC GC — the differential oracle.

An intentionally simple, loop-based implementation of the same rules the TPU
kernel (ops/merge_gc.py) implements with segmented ops. Used by randomized
differential tests, mirroring the reference's model-check strategy
(ref: docdb/randomized_docdb-test.cc + docdb/in_mem_docdb.h) against the real
filter semantics (ref: docdb/docdb_compaction_filter.cc:74-320).

Entries: (key_prefix: bytes, doc_key_len: int, dht: DocHybridTime,
          is_tombstone, is_object_init, ttl_ms or None, payload_id)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime


@dataclass(frozen=True)
class ModelEntry:
    key: bytes
    doc_key_len: int
    dht: DocHybridTime
    is_tombstone: bool = False
    is_object_init: bool = False
    ttl_ms: Optional[int] = None
    payload_id: int = 0


@dataclass(frozen=True)
class ModelResult:
    entry: ModelEntry
    as_tombstone: bool = False  # value rewritten to tombstone (TTL expiry)


def sort_key(e: ModelEntry):
    """Internal key order: key asc, then DocHybridTime DESC."""
    return (e.key, -e.dht.ht.value, -e.dht.write_id)


def compact_model(entries: List[ModelEntry], history_cutoff_ht: int,
                  is_major: bool, retain_deletes: bool = False) -> List[ModelResult]:
    ordered = sorted(entries, key=sort_key)
    cutoff_phys_us = history_cutoff_ht >> 12

    def expired(e: ModelEntry) -> bool:
        if e.ttl_ms is None:
            return False
        return (e.dht.ht.physical_micros + e.ttl_ms * 1000) <= cutoff_phys_us

    # Pass 1: per-doc root overwrite DocHybridTime = the root-level version
    # visible at the cutoff (if any).
    root_ov: dict = {}
    seen_visible: dict = {}
    for e in ordered:
        doc = e.key[: e.doc_key_len]
        is_root = len(e.key) == e.doc_key_len
        below = e.dht.ht.value <= history_cutoff_ht
        if is_root and below and e.key not in seen_visible:
            seen_visible[e.key] = e.dht
            root_ov.setdefault(doc, e.dht)

    # Pass 2: keep/drop per entry.
    out: List[ModelResult] = []
    visible_taken: dict = {}
    for e in ordered:
        below = e.dht.ht.value <= history_cutoff_ht
        if below:
            if e.key in visible_taken:
                continue  # an earlier (newer) <=cutoff version shadows it
            visible_taken[e.key] = True
        is_root = len(e.key) == e.doc_key_len
        if not is_root:
            ov = root_ov.get(e.key[: e.doc_key_len])
            if ov is not None and (e.dht.ht.value, e.dht.write_id) <= (ov.ht.value, ov.write_id):
                continue  # overwritten by a root write visible at cutoff
        tomb = e.is_tombstone or (expired(e) and below)
        if below and tomb and is_major and not retain_deletes:
            continue  # visible tombstone at bottommost level: gone for good
        out.append(ModelResult(e, as_tombstone=(expired(e) and below
                                                and not e.is_tombstone and not is_major)))
    return out
