"""QLProcessor: analyze + execute parsed YCQL statements over the client.

Capability parity with the reference (ref: src/yb/yql/cql/ql/ — analyzer in
ptree/, executor in exec/executor.cc, QLProcessor ql_processor.h:65 with its
parse-tree cache for prepared statements). Semantics carried over:

- INSERT is an upsert; UPDATE touches only assigned columns.
- SELECT with the full primary key is a point read; with only the hash key
  it scans one partition; otherwise a (filtered) full scan.
- BEGIN TRANSACTION ... END TRANSACTION runs its DML atomically through a
  snapshot-isolated distributed transaction, retried on conflict like the
  reference's CQL transaction retry loop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.transaction import (
    TransactionError, TransactionManager)
from yugabyte_tpu.common import jsonb
from yugabyte_tpu.common.hybrid_time import HybridTime
from yugabyte_tpu.common.schema import (
    ColumnSchema, DataType, Schema, SortingType)
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.yql import bfunc
from yugabyte_tpu.yql import index_maintenance as IM
from yugabyte_tpu.yql.cql import parser as P

_CQL_TYPES = {
    "TEXT": DataType.STRING, "VARCHAR": DataType.STRING,
    "INT": DataType.INT32, "BIGINT": DataType.INT64,
    "COUNTER": DataType.INT64, "SMALLINT": DataType.INT32,
    "DOUBLE": DataType.DOUBLE, "FLOAT": DataType.FLOAT,
    "BOOLEAN": DataType.BOOL, "BLOB": DataType.BINARY,
    "TIMESTAMP": DataType.TIMESTAMP, "UUID": DataType.STRING,
    "TIMEUUID": DataType.STRING, "VARINT": DataType.INT64,
    "JSONB": DataType.JSONB,
}


_CQL_AGGS = ("count", "sum", "avg", "min", "max")


def _extract_cql_aggregates(items):
    """[(func, col_or_None)] when EVERY select item is an aggregate call
    over a bare column (or COUNT(*)); None when no item is. Mixing
    aggregates and plain columns is invalid in CQL (no GROUP BY)."""
    def is_agg(i):
        return (isinstance(i, P.FuncCall) and i.name.lower() in _CQL_AGGS
                and len(i.args) == 1
                and (i.args[0] == "*"
                     or isinstance(i.args[0], P.ColumnRef)))
    flags = [is_agg(i) for i in items]
    if not any(flags):
        return None
    if not all(flags):
        raise StatusError(Status.InvalidArgument(
            "aggregates cannot be mixed with plain columns (no GROUP "
            "BY in CQL)"))
    out = []
    for i in items:
        col = None if i.args[0] == "*" else i.args[0].name
        if i.name.lower() != "count" and col is None:
            raise StatusError(Status.InvalidArgument(
                f"{i.name.lower()}(*) is not valid"))
        out.append((i.name.lower(), col))
    return out


def _row_token(row_dict: dict, columns) -> Optional[int]:
    """The row's partition token: the 16-bit hash of its hash-column
    group (ref: token() in the CQL grammar; partition hashing in
    common/partition.py)."""
    vals = tuple(row_dict.get(c) for c in columns)
    if any(v is None for v in vals):
        return None
    return DocKey(hash_components=vals).hash_code


def _jsonb_canonical(v) -> str:
    """Canonicalize a JSONB literal (common/jsonb.py) with CQL errors."""
    try:
        return jsonb.canonicalize(v)
    except ValueError as e:
        raise StatusError(Status.InvalidArgument(f"invalid json: {e}"))


_jsonb_navigate = jsonb.navigate


def _parse_collection_type(t: str):
    """'MAP<TEXT,INT>' -> ("map","TEXT","INT"); 'FROZEN<...>' unwraps.
    None for scalar types (ref: common/ql_type.h)."""
    if t.startswith("FROZEN<") and t.endswith(">"):
        t = t[7:-1]
    for kind in ("LIST", "SET", "MAP"):
        if t.startswith(kind + "<") and t.endswith(">"):
            inner = t[len(kind) + 1:-1].split(",")
            return (kind.lower(),) + tuple(x.strip() for x in inner)
    return None


def _collection_to_storage(coll: tuple, v):
    """CQL literal -> the subdocument dict stored under the column
    (set elements -> {elem: True}; list -> {index: elem})."""
    if v is P.MARKER or (isinstance(v, (list, tuple, set, frozenset))
                         and any(x is P.MARKER for x in v)) \
            or (isinstance(v, dict)
                and any(k is P.MARKER or x is P.MARKER
                        for k, x in v.items())):
        # bind markers inside collection values are not plumbed through
        # the typed prepared-statement path — fail loudly, not with a
        # sentinel stored as data
        raise StatusError(Status.NotSupported(
            "bind markers in collection values: inline the literal"))
    kind = coll[0]
    if kind == "map":
        if not isinstance(v, dict):
            raise StatusError(Status.InvalidArgument(
                f"expected a map literal, got {type(v).__name__}"))
        return dict(v)
    if kind == "set":
        if isinstance(v, dict) and not v:
            v = set()  # '{}' parses as an empty map literal
        if not isinstance(v, (set, frozenset, list, tuple)):
            raise StatusError(Status.InvalidArgument(
                f"expected a set literal, got {type(v).__name__}"))
        return {e: True for e in v}
    if not isinstance(v, (list, tuple)):
        raise StatusError(Status.InvalidArgument(
            f"expected a list literal, got {type(v).__name__}"))
    return {i: e for i, e in enumerate(v)}


def _collection_from_storage(coll: tuple, d):
    """Stored subdocument dict -> the CQL-shaped value (map dict,
    sorted-element set-as-list, index-ordered list)."""
    if not isinstance(d, dict):
        return d
    kind = coll[0]
    if kind == "map":
        return d
    if kind == "set":
        try:
            return sorted(d.keys())
        except TypeError:
            return list(d.keys())
    return [d[k] for k in sorted(d.keys(),
                                 key=lambda x: (not isinstance(x, int), x))]


@dataclass
class ResultSet:
    columns: List[str] = field(default_factory=list)
    rows: List[List[object]] = field(default_factory=list)
    # column DataTypes (parallel to columns; None where unknown) and the
    # source (keyspace, table) — consumed by the binary protocol front end
    # for Rows result metadata
    types: List[Optional[DataType]] = field(default_factory=list)
    source: Tuple[str, str] = ("", "")
    # opaque continuation token: more rows may remain; resume by re-running
    # the same statement with paging_state=this (ref CQL paging protocol)
    paging_state: Optional[bytes] = None

    def dicts(self) -> List[dict]:
        return [dict(zip(self.columns, r)) for r in self.rows]


def _encode_page_state(lower: bytes, cursor: bytes, read_ht: int,
                       remaining: Optional[int]) -> bytes:
    """Opaque SELECT continuation: resume doc-key bound, partition cursor,
    pinned snapshot read time and LIMIT budget left."""
    import struct as _s
    rem = -1 if remaining is None else remaining
    return (_s.pack(">QqII", read_ht, rem, len(lower), len(cursor))
            + lower + cursor)


def _decode_page_state(tok: bytes):
    import struct as _s
    read_ht, rem, nl, nc = _s.unpack(">QqII", tok[:24])
    lower = tok[24:24 + nl]
    cursor = tok[24 + nl:24 + nl + nc]
    return lower, cursor, read_ht, (None if rem < 0 else rem)


class QLProcessor:
    """One per CQL connection in the reference; safe to share here."""

    def __init__(self, client: YBClient,
                 txn_manager: Optional[TransactionManager] = None,
                 local_addr: Optional[Tuple[str, int]] = None):
        self._client = client
        self._txn_manager = txn_manager or TransactionManager(client)
        self._keyspace: Optional[str] = None
        # (host, port) of the CQL endpoint this processor serves —
        # reported by the system.local vtable
        self.local_addr = local_addr
        # (keyspace, table) -> (handle, cached-at monotonic time); see
        # the TTL logic in _table()
        self._tables: Dict[Tuple[str, str], Tuple[YBTable, float]] = {}
        self._stmt_cache: Dict[str, P.Statement] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------------- helpers
    def _resolve_ks(self, ks: Optional[str]) -> str:
        ks = ks or self._keyspace
        if ks is None:
            raise StatusError(Status.InvalidArgument(
                "no keyspace specified (USE <keyspace> or qualify)"))
        return ks

    def _table(self, ks: Optional[str], name: str) -> YBTable:
        """Table-handle cache with a TTL: index DDL elsewhere must become
        visible to this session's writes within the TTL (the schema-version
        propagation window; the reference invalidates on version-mismatch
        errors from the tserver, ref table_schema_version checks)."""
        from yugabyte_tpu.utils import flags as _flags
        ks = self._resolve_ks(ks)
        ttl = _flags.get_flag("table_cache_ttl_ms") / 1000.0
        now = time.monotonic()
        with self._lock:
            entry = self._tables.get((ks, name))
            if entry is not None and now - entry[1] < ttl:
                return entry[0]
        t = self._client.open_table(ks, name)
        with self._lock:
            self._tables[(ks, name)] = (t, now)
        return t

    def _bind_where(self, where, params: List[object],
                    cursor: List[int]):
        """Bind a WHERE conjunction, descending into IN lists (their
        elements may each be a '?' marker)."""
        out = []
        for c, op, v in where:
            if isinstance(v, list):
                out.append((c, op, [self._bind(x, params, cursor)
                                    for x in v]))
            else:
                out.append((c, op, self._bind(v, params, cursor)))
        return out

    @staticmethod
    def _bind(value, params: List[object], cursor: List[int]):
        if value is P.MARKER:
            if cursor[0] >= len(params):
                raise StatusError(Status.InvalidArgument(
                    "not enough bind parameters"))
            v = params[cursor[0]]
            cursor[0] += 1
            return v
        if isinstance(value, P.FuncCall):
            # constant builtin in a value position: now(), uuid(),
            # intasblob(7)... (ref bfql standard functions)
            args = [QLProcessor._bind(a, params, cursor)
                    for a in value.args]
            if any(isinstance(a, P.ColumnRef) for a in args):
                raise StatusError(Status.InvalidArgument(
                    f"{value.name}: column references are not allowed "
                    f"in value expressions"))
            try:
                v, _t = bfunc.evaluate(value.name, args)
            except bfunc.BFError as e:
                raise StatusError(Status.InvalidArgument(str(e)))
            return v
        return value

    # ------------------------------------------------- select-item builtins
    def _item_label(self, item) -> str:
        if isinstance(item, P.FuncCall):
            inner = ", ".join(self._item_label(a) for a in item.args)
            return f"{item.name.lower()}({inner})"
        if isinstance(item, P.ColumnRef):
            return item.name
        if isinstance(item, P.TokenRef):
            return f"token({', '.join(item.columns)})"
        if isinstance(item, P.JsonOp):
            out = item.column
            for i, step in enumerate(item.path):
                arrow = "->>" if (item.as_text
                                  and i == len(item.path) - 1) else "->"
                out += f"{arrow}{step!r}" if isinstance(step, int) \
                    else f"{arrow}'{step}'"
            return out
        return str(item)

    def _item_type(self, item, known, as_column: bool = True):
        """as_column: a bare str is a column name only at the TOP of a
        select item; inside function ARGUMENTS plain strings are string
        literals (columns there are P.ColumnRef)."""
        if isinstance(item, P.FuncCall):
            try:
                d = bfunc.resolve(item.name,
                                  [self._item_type(a, known, False)
                                   for a in item.args])
            except bfunc.BFError as e:
                raise StatusError(Status.InvalidArgument(str(e)))
            return d.ret_type if d.ret_type is not bfunc.ANY else None
        if isinstance(item, P.ColumnRef):
            return known.get(item.name)
        if isinstance(item, P.JsonOp):
            if known.get(item.column) is not DataType.JSONB:
                raise StatusError(Status.InvalidArgument(
                    f"{item.column} is not a jsonb column"))
            return DataType.STRING if item.as_text else DataType.JSONB
        if isinstance(item, P.TokenRef):
            return DataType.INT64
        if isinstance(item, str) and as_column:
            return known.get(item)
        return bfunc.infer_type(item)

    def _compile_item(self, item, known, as_column: bool = True):
        """Compile one select item to fn(row_dict, row) -> value.

        Builtin signatures resolve ONCE per statement (types are fixed),
        not per row (ref: the analyzer binds PTExpr opcodes at prepare
        time). writetime/ttl read Row metadata like the reference's
        TSOpcode path. as_column: see _item_type."""
        if isinstance(item, str) and as_column:
            return lambda d, row, _c=item: d.get(_c)
        if isinstance(item, P.ColumnRef):
            return lambda d, row, _c=item.name: d.get(_c)
        if isinstance(item, P.JsonOp):
            return lambda d, row, _j=item: _jsonb_navigate(
                d.get(_j.column), _j.path, _j.as_text)
        if isinstance(item, P.TokenRef):
            return lambda d, row, _c=item.columns: _row_token(d, _c)
        if isinstance(item, P.FuncCall):
            name = item.name.lower()
            if name == "writetime":
                return lambda d, row: (row.write_ht.physical_micros
                                       if row is not None else None)
            if name == "ttl":
                # per-cell TTL is not retained on the read path
                return lambda d, row: None
            arg_fns = [self._compile_item(a, known, False)
                       for a in item.args]
            types = [self._item_type(a, known, False) for a in item.args]
            try:
                decl = bfunc.resolve(item.name, types)
            except bfunc.BFError as e:
                raise StatusError(Status.InvalidArgument(str(e)))
            if decl.fn is None:
                raise StatusError(Status.InvalidArgument(
                    f"{name} is not valid here"))

            def ev(d, row, _decl=decl, _fns=arg_fns, _n=name):
                try:
                    return _decl.fn(*[f(d, row) for f in _fns])
                except bfunc.BFError as e:
                    raise StatusError(Status.InvalidArgument(str(e)))
                except Exception as e:
                    raise StatusError(Status.InvalidArgument(f"{_n}: {e}"))
            return ev
        return lambda d, row, _v=item: _v

    def _doc_key_from_where(self, table: YBTable,
                            where: List[Tuple[str, str, object]]
                            ) -> Tuple[Optional[DocKey], List]:
        """Split WHERE into a (possibly partial) primary key + residual
        filters (ref ptree analyzer's where-clause classification)."""
        schema = table.schema
        eq: Dict[str, object] = {}
        residual = []
        key_names = {c.name for c in schema.hash_columns} | \
            {c.name for c in schema.range_columns}
        for col, op, val in where:
            if op == "=" and col in key_names and col not in eq:
                eq[col] = val
            else:
                residual.append((col, op, val))
        hash_vals = [eq.get(c.name) for c in schema.hash_columns]
        range_vals = [eq.get(c.name) for c in schema.range_columns]
        if any(v is None for v in hash_vals):
            # No complete hash key: everything is residual filtering.
            return None, where
        while range_vals and range_vals[-1] is None:
            range_vals.pop()
        if any(v is None for v in range_vals):
            raise StatusError(Status.InvalidArgument(
                "range key columns must be constrained left-to-right"))
        return DocKey(hash_components=tuple(hash_vals),
                      range_components=tuple(range_vals)), residual

    _WIRE_LITERALS = (int, float, str, bytes, bool, type(None))

    @classmethod
    def _wire_filters(cls, schema, residual) -> Optional[List[List]]:
        """The subset of residual predicates worth shipping to the
        tserver scan (device-compilable triples run in the fused
        filtered kernel there; the rest evaluate host-side server-side
        before rows cross the wire). Safe by construction: for every
        shipped op the server's FILTER_OPS semantics are a SUPERSET of
        _match's (they differ only on NULLs, where the server may keep
        a row _match drops), and _match re-checks the full residual
        client-side — so pushdown can narrow the wire, never the
        result."""
        out = []
        for c, op, v in residual:
            if not isinstance(c, str) \
                    or op not in ("=", "!=", "<", "<=", ">", ">=", "in"):
                continue
            try:
                col = schema.column(c)
            except KeyError:
                continue
            if col.collection is not None:
                # server-side row dicts hold the STORAGE form of
                # collections; only the executor converts to CQL shapes,
                # so a collection comparison must stay client-side
                continue
            if op == "in":
                if not isinstance(v, (list, tuple)) or not all(
                        isinstance(x, cls._WIRE_LITERALS) for x in v):
                    continue
            elif not isinstance(v, cls._WIRE_LITERALS):
                continue
            out.append([c, op, list(v) if op == "in" else v])
        return out or None

    @staticmethod
    def _match(row_dict: dict, residual: List[Tuple[str, str, object]]
               ) -> bool:
        import operator
        ops = {"=": operator.eq, "!=": operator.ne, "<": operator.lt,
               ">": operator.gt, "<=": operator.le, ">=": operator.ge}
        for col, op, val in residual:
            if isinstance(col, P.JsonOp):
                have = _jsonb_navigate(row_dict.get(col.column),
                                       col.path, col.as_text)
            elif isinstance(col, P.TokenRef):
                have = _row_token(row_dict, col.columns)
            else:
                have = row_dict.get(col)
            if have is None:
                return False
            if op == "in":
                if have not in val:
                    return False
            elif not ops[op](have, val):
                return False
        return True

    # -------------------------------------------------------------- execute
    def execute(self, text: str, params: Sequence[object] = (),
                page_size: Optional[int] = None,
                paging_state: Optional[bytes] = None) -> ResultSet:
        """Parse (with statement-cache, ref QLProcessor prepared stmts) and
        run one statement.

        page_size/paging_state: result paging for SELECT (ref the CQL
        paging protocol + pgsql_operation.cc:1040 paging state) — at most
        page_size rows return, with ResultSet.paging_state set when more
        may remain; resuming with that opaque token continues the scan at
        the pinned snapshot read time."""
        with self._lock:
            stmt = self._stmt_cache.get(text)
        if stmt is None:
            stmt = P.parse(text)
            # Cache only parameterized statements (the reference caches
            # PREPARED statements); inline-literal texts are unique per
            # call and would grow the cache without bound.
            if "?" in text:
                with self._lock:
                    if len(self._stmt_cache) > 4096:
                        self._stmt_cache.clear()
                    self._stmt_cache[text] = stmt
        return self._execute_stmt(stmt, list(params), page_size=page_size,
                                  paging_state=paging_state)

    def _execute_stmt(self, stmt: P.Statement, params: List[object],
                      page_size: Optional[int] = None,
                      paging_state: Optional[bytes] = None) -> ResultSet:
        cursor = [0]
        if isinstance(stmt, P.CreateKeyspace):
            try:
                self._client.create_namespace(stmt.name)
            except StatusError as e:
                if not (stmt.if_not_exists
                        and e.status.code.name == "ALREADY_PRESENT"):
                    raise
            return ResultSet()
        if isinstance(stmt, P.UseKeyspace):
            self._keyspace = stmt.name
            return ResultSet()
        if isinstance(stmt, P.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, P.DropTable):
            ks = self._resolve_ks(stmt.keyspace)
            try:
                self._client.delete_table(ks, stmt.name)
            except StatusError as e:
                if not (stmt.if_exists
                        and e.status.code.name == "NOT_FOUND"):
                    raise
            with self._lock:
                self._tables.pop((ks, stmt.name), None)
            return ResultSet()
        if isinstance(stmt, P.AlterTable):
            return self._alter_table(stmt)
        if isinstance(stmt, P.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, P.Select):
            ks = stmt.keyspace or self._keyspace
            if ks in ("system", "system_schema"):
                if stmt.columns and _extract_cql_aggregates(
                        stmt.columns) is not None:
                    raise StatusError(Status.NotSupported(
                        "aggregates over system tables"))
                return self._select_system(ks, stmt, params, cursor)
            return self._select(stmt, params, cursor, page_size=page_size,
                                page_state=paging_state)
        if isinstance(stmt, (P.Insert, P.Update, P.Delete)):
            if getattr(stmt, "if_not_exists", False) \
                    or getattr(stmt, "if_exists", False) \
                    or getattr(stmt, "conditions", None):
                return self._conditional_dml(stmt, params, cursor)
            table, op = self._dml_to_op(stmt, params, cursor)
            ks = self._resolve_ks(getattr(stmt, "keyspace", None))
            IM.write_with_indexes(
                self._client, self._txn_manager, table, op,
                lambda name, _ks=ks: self._table(_ks, name))
            return ResultSet()
        if isinstance(stmt, P.Transaction):
            return self._run_transaction(stmt, params)
        if isinstance(stmt, P.Truncate):
            return self._truncate(stmt)
        raise StatusError(Status.NotSupported(f"statement {type(stmt)}"))

    def _select_distinct(self, stmt: P.Select, params, cursor,
                         page_size=None, page_state=None) -> ResultSet:
        """SELECT DISTINCT over the partition key: CQL restricts DISTINCT
        to EXPLICIT partition key columns (no '*'), without ORDER BY —
        one output row per partition (ref: the grammar's distinct
        restriction in ql). Pages by offset into the distinct set (the
        set is bounded by the partition count)."""
        table = self._table(stmt.keyspace, stmt.table)
        schema = table.schema
        hash_names = [c.name for c in schema.hash_columns]
        if stmt.columns is None:
            raise StatusError(Status.InvalidArgument(
                "SELECT DISTINCT * is not valid: name the partition "
                f"key columns {hash_names}"))
        if stmt.order_by:
            raise StatusError(Status.InvalidArgument(
                "ORDER BY is not valid with SELECT DISTINCT"))
        want = stmt.columns
        if [c for c in want if not isinstance(c, str)] \
                or list(want) != hash_names:
            raise StatusError(Status.InvalidArgument(
                f"SELECT DISTINCT is only valid on the partition key "
                f"columns {hash_names}"))
        inner = P.Select(stmt.keyspace, stmt.table, list(hash_names),
                         stmt.where, None)
        rs = self._select(inner, params, cursor)
        seen = []
        seen_set = set()
        for row in rs.rows:
            t = tuple(row)
            if t not in seen_set:
                seen_set.add(t)
                seen.append(list(row))
                if stmt.limit is not None and len(seen) >= stmt.limit:
                    break
        off = 0
        if page_state:
            try:
                if not page_state.startswith(b"DIST:"):
                    raise ValueError(page_state)
                off = int(page_state[5:])
            except ValueError:
                raise StatusError(Status.InvalidArgument(
                    "malformed paging state"))
        out = ResultSet(columns=list(hash_names),
                        types=[schema.column(c).type
                               for c in hash_names],
                        source=rs.source)
        if page_size is not None:
            out.rows = seen[off:off + page_size]
            if off + page_size < len(seen):
                out.paging_state = b"DIST:%d" % (off + page_size)
        else:
            out.rows = seen[off:]
        return out

    def _select_aggregate(self, stmt: P.Select, aggs, params, cursor
                          ) -> ResultSet:
        """CQL aggregates: COUNT(*)/COUNT(col)/SUM/AVG/MIN/MAX over the
        whole (filtered) result — YCQL has no GROUP BY, so the output is
        exactly one row (ref: the CQL aggregate surface in the
        reference's ql; Cassandra 2.2 aggregate semantics — AVG over an
        int column is integer division).

        When the whole (WHERE, aggregate-list) pair is inside the device
        subset (docdb/scan_spec.py), the scalars come back from ONE
        fused segment-reduce dispatch per tablet instead of every row
        surfacing to this process (ROADMAP item 5); tablets that cannot
        push return rows, which fold into the same accumulator with
        identical semantics. The output row is assembled from the stats
        by ONE shared code path either way."""
        table = self._table(stmt.keyspace, stmt.table)
        stats = None
        if not stmt.order_by:
            stats = self._try_pushdown_aggregate(stmt, aggs, params,
                                                 cursor, table)
        if stats is None:
            cols_needed = sorted({c for _f, c in aggs if c is not None})
            if not cols_needed:
                # COUNT(*)-only: project one key column, not the whole row
                cols_needed = [table.schema.hash_columns[0].name]
            # LIMIT applies to the RESULT rows (exactly one for an
            # aggregate), not to the scan feeding it: `SELECT COUNT(*)
            # ... LIMIT 1` must count every matching row, so the inner
            # scan is unlimited
            inner = P.Select(stmt.keyspace, stmt.table,
                             cols_needed, stmt.where, None,
                             order_by=stmt.order_by)
            rs = self._select(inner, params, cursor)
            stats = self._agg_stats_from_dicts(aggs, rs.dicts())
        return self._assemble_aggregate(aggs, table, stats)

    @staticmethod
    def _agg_stats_from_dicts(aggs, dicts) -> dict:
        """Host-path accumulator: per aggregated column, the non-null
        value list (assembly reduces it per requested function)."""
        cols: Dict[str, dict] = {}
        for _fname, col in aggs:
            if col is None or col in cols:
                continue
            vals = [d.get(col) for d in dicts if d.get(col) is not None]
            cols[col] = {"nonnull": len(vals), "vals": vals}
        return {"rows": len(dicts), "cols": cols}

    def _assemble_aggregate(self, aggs, table, stats) -> ResultSet:
        """stats -> the single CQL aggregate output row. stats["cols"]
        entries carry either a host value list ("vals") or the device
        partial scalars ("sum"/"min"/"max") — reductions are exact ints
        on the device path, so both shapes produce identical output."""
        known = {c.name: c.type for c in table.schema.columns}
        empty = {"nonnull": 0, "vals": []}
        out_row: List[object] = []
        out_cols: List[str] = []
        out_types: List[Optional[DataType]] = []
        for fname, col in aggs:
            label = f"{fname}({'*' if col is None else col})"
            out_cols.append(label)
            if fname == "count":
                out_row.append(stats["rows"] if col is None
                               else stats["cols"].get(col, empty)["nonnull"])
                out_types.append(DataType.INT64)
                continue
            st = stats["cols"].get(col, empty)
            nn = st["nonnull"]
            t = known.get(col)
            if fname in ("sum", "avg") and t not in (
                    DataType.INT32, DataType.INT64, DataType.FLOAT,
                    DataType.DOUBLE):
                raise StatusError(Status.InvalidArgument(
                    f"{fname}() requires a numeric column"))
            if fname == "sum":
                total = sum(st["vals"]) if "vals" in st else st["sum"]
                out_row.append(total if nn else 0)
                # a sum of int32s overflows int32: widen on the wire
                out_types.append(DataType.INT64
                                 if t == DataType.INT32 else t)
            elif fname == "avg":
                total = sum(st["vals"]) if "vals" in st else st["sum"]
                if not nn:
                    out_row.append(0)
                elif t in (DataType.INT32, DataType.INT64):
                    out_row.append(total // nn)
                else:
                    out_row.append(total / nn)
                out_types.append(t)
            else:  # min / max
                try:
                    if "vals" in st:
                        out_row.append(
                            (min if fname == "min" else max)(st["vals"])
                            if nn else None)
                    else:
                        out_row.append(st[fname])
                except TypeError:
                    raise StatusError(Status.InvalidArgument(
                        f"{fname}() requires a comparable column type"))
                out_types.append(t)
        return ResultSet(columns=out_cols, rows=[out_row],
                         types=out_types,
                         source=(table.namespace, table.name))

    def _try_pushdown_aggregate(self, stmt: P.Select, aggs, params,
                                cursor, table) -> Optional[dict]:
        """Attempt the fused-aggregate path. Returns the device-shaped
        stats dict, or None when the statement is outside the pushdown
        shape (the caller runs the unchanged host path; parameter
        binding happens on a TRIAL cursor so a refusal consumes
        nothing). Fallback-tablet rows are re-checked with the
        executor's own _match before folding, so the combined stats
        carry executor semantics exactly — including the
        NULL-fails-every-operator rule."""
        from yugabyte_tpu.docdb import scan_spec as SS
        schema = table.schema
        wire_aggs = []
        for fname, col in aggs:
            fn = "sum" if fname == "avg" else fname
            if SS.compile_aggregate(schema, fn, col) is None:
                return None
            wire_aggs.append([fn, col])
        trial = [cursor[0]]
        where = self._bind_where(stmt.where, params, trial)
        known = {c.name: c.type for c in schema.columns}
        where = self._canon_jsonb_where(where, known)
        for c, op, _v in where:
            if not isinstance(c, str) or op == "in":
                return None
        dk, residual = self._doc_key_from_where(table, where)
        if dk is not None and len(dk.range_components) \
                == schema.num_range_key_columns:
            return None   # full primary key: the point read is optimal
        key_names = {c.name for c in schema.hash_columns} | \
            {c.name for c in schema.range_columns}
        partition_key = None
        lo = b""
        hi = None
        if dk is not None:
            prefix = DocKey(hash_components=dk.hash_components,
                            range_components=dk.range_components).encode()
            prefix = prefix[:-1]
            lo, hi = self._range_scan_bounds(schema, dk, prefix, residual)
            partition_key = table.partition_key_for(dk)
            residual = [r for r in residual
                        if not self._bound_enforces(schema, dk, r)]
        preds = []
        for c, op, v in residual:
            if c in key_names:
                # a key-component predicate the byte bounds don't fully
                # enforce: outside the scalar-aggregate shape
                return None
            if SS.compile_predicate(schema, c, op, v) is None:
                return None
            preds.append([c, op, v])
        cursor[0] = trial[0]
        fb_dicts: List[dict] = []

        def on_row(row):
            d = self._row_dict(schema, row)
            if self._match(d, residual):
                fb_dicts.append(d)

        partial, _read_ht = self._client.scan_aggregate(
            table, wire_aggs, filters=preds,
            partition_key=partition_key, lower_doc_key=lo,
            upper_doc_key=hi, row_cb=on_row)
        cid_to_name = {schema.column_id(c.name): c.name
                       for c in schema.value_columns}
        stats = {"rows": 0, "cols": {}}
        if partial is not None:
            stats["rows"] = partial["rows"]
            for cid, st in partial["cols"].items():
                name = cid_to_name.get(int(cid))
                if name is not None:
                    stats["cols"][name] = dict(st)
        # fold the host-checked fallback rows (disjoint tablet sets, so
        # adding counts/sums and reducing extremes is exact) — once per
        # DISTINCT aggregated column, however many functions name it
        stats["rows"] += len(fb_dicts)
        for col in dict.fromkeys(c for _f, c in aggs if c is not None):
            st = stats["cols"].setdefault(
                col, {"nonnull": 0, "sum": 0, "min": None, "max": None})
            vals = [d.get(col) for d in fb_dicts
                    if d.get(col) is not None]
            st["nonnull"] += len(vals)
            if vals:
                st["sum"] = st.get("sum", 0) + sum(vals)
                st["min"] = min(vals) if st.get("min") is None \
                    else min(st["min"], *vals)
                st["max"] = max(vals) if st.get("max") is None \
                    else max(st["max"], *vals)
        return stats

    @staticmethod
    def _bound_enforces(schema, dk, pred) -> bool:
        """True when _range_scan_bounds absorbed this residual predicate
        into an EXACT byte bound: an inequality on the first unbound
        clustering column with a correctly-typed literal. (Component
        encoding is order-preserving and every longer key continues
        with a tag byte < 0xff, so the prefix+encode(v) bounds include/
        exclude exactly the predicate's rows — no edge slack.)"""
        c, op, v = pred
        bound_n = len(dk.range_components)
        if bound_n >= len(schema.range_columns):
            return False
        nxt_col = schema.range_columns[bound_n]
        if c != nxt_col.name or op not in ("<", "<=", ">", ">="):
            return False
        if not QLProcessor._bound_type_ok(nxt_col.type, v):
            return False
        from yugabyte_tpu.docdb.doc_key import PrimitiveValue
        try:
            PrimitiveValue.encode(v, bytearray())
        except TypeError:
            return False
        return True

    def _conditional_dml(self, stmt, params: List[object],
                         cursor: List[int]) -> ResultSet:
        """Lightweight transaction: INSERT ... IF NOT EXISTS, UPDATE/
        DELETE ... IF EXISTS / IF <conds>. Runs as a read-check-write
        distributed transaction with conflict retry, returning the CQL
        [applied] row — with the current row's values when not applied
        (ref: the conditional QLWriteRequest if_expr path; the analyzer's
        if-clause handling in ql/ptree/pt_dml.h)."""
        table, op = self._dml_to_op(stmt, params, cursor)
        # IF conditions bind AFTER the WHERE clause (statement-text order)
        conds = [(c, o, self._bind(v, params, cursor))
                 for c, o, v in getattr(stmt, "conditions", [])]
        ks = self._resolve_ks(getattr(stmt, "keyspace", None))
        schema = table.schema
        insert_mode = getattr(stmt, "if_not_exists", False)

        def body(txn):
            row = txn.read_row(table, op.doc_key)
            d = self._row_dict(schema, row) if row is not None else None
            if insert_mode:
                applied = row is None
            elif conds:
                applied = d is not None and self._match(d, conds)
            else:  # IF EXISTS
                applied = row is not None
            if applied:
                IM.txn_write_with_indexes(
                    txn, table, op,
                    lambda name, _ks=ks: self._table(_ks, name),
                    old_row_dict=d if d is not None else {})
            return applied, d

        applied, d = IM.run_in_implicit_txn(
            self._txn_manager, None, body, 30.0)
        rs = ResultSet(columns=["[applied]"], types=[DataType.BOOL])
        if applied or d is None:
            rs.rows.append([applied])
        else:
            # not applied: CQL returns the current values alongside
            # [applied] = false so clients can see why the CAS failed
            extra = sorted(d) if insert_mode else \
                list(dict.fromkeys(c for c, _o, _v in conds)) or sorted(d)
            rs.columns += extra
            rs.types += [schema.column(c).type if self._has_col(schema, c)
                         else None for c in extra]
            rs.rows.append([applied] + [d.get(c) for c in extra])
        return rs

    @staticmethod
    def _has_col(schema, name: str) -> bool:
        try:
            schema.column(name)
            return True
        except KeyError:
            return False

    def _truncate(self, stmt: P.Truncate) -> ResultSet:
        """Delete every row (and maintained index rows) from the table.
        Functional equivalent of the reference's whole-tablet truncate
        (tablet.cc Truncate), expressed through the row delete path so
        secondary indexes stay consistent."""
        ks = self._resolve_ks(stmt.keyspace)
        table = self._table(stmt.keyspace, stmt.table)

        def flush(ops: List[QLWriteOp]) -> None:
            if not table.indexes:
                self._client.write(table, ops)
                return
            # one implicit distributed txn per BATCH (not per row): the
            # batch's main-row + index-row deletes commit atomically
            IM.run_in_implicit_txn(
                self._txn_manager, None,
                lambda txn: [IM.txn_write_with_indexes(
                    txn, table, op,
                    lambda name, _ks=ks: self._table(_ks, name))
                    for op in ops],
                30.0)

        batch: List[QLWriteOp] = []
        for row in self._client.scan(table):
            batch.append(QLWriteOp(WriteOpKind.DELETE_ROW, row.doc_key))
            if len(batch) >= 512:
                flush(batch)
                batch = []
        if batch:
            flush(batch)
        return ResultSet()

    def _alter_table(self, stmt: P.AlterTable) -> ResultSet:
        """ALTER TABLE ADD/DROP column riding the master's versioned
        online schema change (ref ql/ptree/pt_alter_table.h)."""
        ks = self._resolve_ks(stmt.keyspace)
        add = []
        for col, cql_t in stmt.add_columns:
            t = cql_t.upper()
            if t not in _CQL_TYPES:
                raise StatusError(Status.NotSupported(f"type {t}"))
            add.append((col, _CQL_TYPES[t].value))
        self._client.alter_table(ks, stmt.name, add_columns=add,
                                 drop_columns=stmt.drop_columns)
        with self._lock:
            self._tables.pop((ks, stmt.name), None)
        return ResultSet()

    def _create_index(self, stmt: P.CreateIndex) -> ResultSet:
        ks = self._resolve_ks(stmt.keyspace)
        index_name = stmt.index_name \
            or f"{stmt.table}_{'_'.join(stmt.columns)}_idx"
        try:
            self._client.create_index(ks, stmt.table, index_name,
                                      list(stmt.columns))
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code.name == "ALREADY_PRESENT"):
                raise
        with self._lock:
            self._tables.pop((ks, stmt.table), None)  # refresh index list
        return ResultSet()

    def _create_table(self, stmt: P.CreateTable) -> ResultSet:
        ks = self._resolve_ks(stmt.keyspace)
        key_order = stmt.hash_keys + stmt.range_keys
        cols_by_name = dict(stmt.columns)
        unknown = [k for k in key_order if k not in cols_by_name]
        if unknown:
            raise StatusError(Status.InvalidArgument(
                f"primary key columns not defined: {unknown}"))
        ordered = key_order + [n for n, _t in stmt.columns
                               if n not in key_order]
        columns = []
        for n in ordered:
            cql_t = cols_by_name[n].upper()
            coll = _parse_collection_type(cql_t)
            if coll is not None:
                if n in key_order:
                    # FROZEN keys would need a canonical bytes encoding of
                    # the collection as a DocKey component — unsupported
                    raise StatusError(Status.NotSupported(
                        f"collection column {n} cannot be a key"))
                columns.append(ColumnSchema(n, DataType.BINARY,
                                            collection=coll))
                continue
            if cql_t not in _CQL_TYPES:
                raise StatusError(Status.NotSupported(f"type {cql_t}"))
            if _CQL_TYPES[cql_t] is DataType.JSONB and n in key_order:
                # jsonb has no order-preserving key encoding (the
                # reference likewise rejects jsonb primary keys)
                raise StatusError(Status.NotSupported(
                    f"jsonb column {n} cannot be a key"))
            columns.append(ColumnSchema(n, _CQL_TYPES[cql_t]))
        schema = Schema(columns=columns,
                        num_hash_key_columns=len(stmt.hash_keys),
                        num_range_key_columns=len(stmt.range_keys))
        try:
            self._client.create_table(ks, stmt.name, schema,
                                      num_tablets=stmt.num_tablets)
        except StatusError as e:
            if not (stmt.if_not_exists
                    and e.status.code.name == "ALREADY_PRESENT"):
                raise
        return ResultSet()

    def _dml_to_op(self, stmt, params: List[object],
                   cursor: List[int]) -> Tuple[YBTable, QLWriteOp]:
        if isinstance(stmt, P.Insert):
            table = self._table(stmt.keyspace, stmt.table)
            schema = table.schema
            bound = {c: self._bind(v, params, cursor)
                     for c, v in zip(stmt.columns, stmt.values)}
            key_names = [c.name for c in schema.hash_columns] + \
                [c.name for c in schema.range_columns]
            missing = [k for k in key_names if k not in bound]
            if missing:
                raise StatusError(Status.InvalidArgument(
                    f"INSERT missing key columns {missing}"))
            dk = DocKey(
                hash_components=tuple(bound[c.name]
                                      for c in schema.hash_columns),
                range_components=tuple(bound[c.name]
                                       for c in schema.range_columns))
            values = {c: v for c, v in bound.items()
                      if c not in key_names}
            coll_ops = {}
            for c in list(values):
                coll = self._collection_of(schema, c)
                if coll is not None and values[c] is not None:
                    coll_ops[c] = [("replace",
                                    _collection_to_storage(coll,
                                                           values.pop(c)))]
                elif values[c] is not None and self._is_jsonb(schema, c):
                    values[c] = _jsonb_canonical(values[c])
            return table, QLWriteOp(
                WriteOpKind.INSERT, dk, values, collection_ops=coll_ops,
                ttl_ms=stmt.ttl_seconds * 1000 if stmt.ttl_seconds else None)
        if isinstance(stmt, P.Update):
            table = self._table(stmt.keyspace, stmt.table)
            schema = table.schema
            # Bind in statement-text order: SET comes before WHERE.
            assignments = [(c, self._bind(v, params, cursor))
                           for c, v in stmt.assignments]
            where = self._bind_where(stmt.where, params, cursor)
            dk, residual = self._doc_key_from_where(table, where)
            if dk is None or residual:
                raise StatusError(Status.InvalidArgument(
                    "UPDATE requires the full primary key"))
            values = {}
            # ORDERED op list per column: mixed element writes and deletes
            # in one UPDATE apply in statement order (later wins at the
            # same path via ascending intra-batch write ids)
            coll_ops: Dict[str, List[Tuple[str, object]]] = {}

            for c, v in assignments:
                if isinstance(c, tuple):        # m['k'] = v  /  l[i] = v
                    col, sub = c
                    coll = self._collection_of(schema, col)
                    if coll is None:
                        raise StatusError(Status.InvalidArgument(
                            f"{col} is not a collection"))
                    ops = coll_ops.setdefault(col, [])
                    if v is None:
                        ops.append(("del_keys", [sub]))
                    else:
                        ops.append(("merge", {sub: v}))
                    continue
                coll = self._collection_of(schema, c)
                if coll is None:
                    if isinstance(v, tuple) and len(v) == 2 \
                            and v[0] in ("__append__", "__remove__"):
                        raise StatusError(Status.InvalidArgument(
                            f"{c} is not a collection: col = col +/- X "
                            f"applies to collections only"))
                    if v is not None and self._is_jsonb(schema, c):
                        v = _jsonb_canonical(v)
                    values[c] = v
                    continue
                if isinstance(v, tuple) and len(v) == 2 \
                        and v[0] in ("__append__", "__remove__"):
                    lit = v[1]
                    if coll[0] == "list":
                        # lists store {index: elem}; value-based +/- would
                        # need read-modify-write — be explicit, not wrong
                        raise StatusError(Status.NotSupported(
                            "list +/-: assign the full list"))
                    if v[0] == "__append__":
                        coll_ops.setdefault(c, []).append(
                            ("merge", _collection_to_storage(coll, lit)))
                    else:
                        elems = list(lit.keys()) if isinstance(lit, dict) \
                            else list(lit)
                        coll_ops.setdefault(c, []).append(
                            ("del_keys", elems))
                elif v is None:
                    values[c] = None  # whole-collection delete (tombstone)
                else:
                    coll_ops.setdefault(c, []).append(
                        ("replace", _collection_to_storage(coll, v)))
            return table, QLWriteOp(
                WriteOpKind.UPDATE, dk, values, collection_ops=coll_ops,
                ttl_ms=stmt.ttl_seconds * 1000 if stmt.ttl_seconds else None)
        # Delete
        table = self._table(stmt.keyspace, stmt.table)
        where = self._bind_where(stmt.where, params, cursor)
        dk, residual = self._doc_key_from_where(table, where)
        if dk is None or residual:
            raise StatusError(Status.InvalidArgument(
                "DELETE requires the full primary key"))
        if stmt.columns:
            plain = [c for c in stmt.columns if not isinstance(c, tuple)]
            coll_ops: Dict[str, List[Tuple[str, object]]] = {}
            for c in stmt.columns:
                if isinstance(c, tuple):        # DELETE m['k'] FROM ...
                    col, sub = c
                    if self._collection_of(table.schema, col) is None:
                        raise StatusError(Status.InvalidArgument(
                            f"{col} is not a collection"))
                    coll_ops.setdefault(col, []).append(("del_keys",
                                                         [sub]))
            return table, QLWriteOp(WriteOpKind.DELETE_COLS, dk,
                                    columns_to_delete=tuple(plain),
                                    collection_ops=coll_ops)
        return table, QLWriteOp(WriteOpKind.DELETE_ROW, dk)

    @staticmethod
    def _collection_of(schema, name: str):
        try:
            return schema.column(name).collection
        except KeyError:
            return None

    @staticmethod
    def _canon_jsonb_where(where, known):
        """Jsonb predicates: reject -> on non-jsonb columns, and
        canonicalize comparison values where the lhs yields json text
        (whole-document equality, or a -> chain without ->>) so equal
        documents match regardless of literal spelling — the stored form
        is canonical (common/jsonb.py)."""
        out = []
        for c, op, v in where:
            canon = False
            if isinstance(c, P.JsonOp):
                if known.get(c.column) is not DataType.JSONB:
                    raise StatusError(Status.InvalidArgument(
                        f"{c.column} is not a jsonb column"))
                canon = not c.as_text
            elif isinstance(c, str) and known.get(c) is DataType.JSONB:
                canon = True
            if canon and v is not None:
                if op == "in":
                    v = [_jsonb_canonical(x) if x is not None else None
                         for x in v]
                else:
                    v = _jsonb_canonical(v)
            out.append((c, op, v))
        return out

    @staticmethod
    def _is_jsonb(schema, name: str) -> bool:
        try:
            return schema.column(name).type is DataType.JSONB
        except KeyError:
            return False

    def _row_dict(self, schema, row):
        """Row -> dict with collection columns converted from their
        subdocument storage form to CQL shapes (map/set/list)."""
        d = row.to_dict(schema)
        for c in schema.value_columns:
            if c.collection is not None and d.get(c.name) is not None:
                d[c.name] = _collection_from_storage(c.collection,
                                                     d[c.name])
        return d

    def _select(self, stmt: P.Select, params: List[object],
                cursor: List[int], page_size: Optional[int] = None,
                page_state: Optional[bytes] = None) -> ResultSet:
        table = self._table(stmt.keyspace, stmt.table)
        schema = table.schema

        def bind_item(it):
            """Bind '?' markers inside select-list builtin calls. Select
            items are bound BEFORE the WHERE clause: positional params
            arrive in statement-text order."""
            if isinstance(it, P.FuncCall):
                return P.FuncCall(it.name, [bind_item(a) for a in it.args])
            if it is P.MARKER:
                return self._bind(it, params, cursor)
            return it

        out_items = [bind_item(i)
                     for i in (stmt.columns
                               or [c.name for c in schema.columns
                                   if not c.dropped])]
        # token() must name the partition key columns in order — a hash
        # over anything else matches no partition layout (real CQL
        # rejects it the same way)
        hash_col_names = [c.name for c in schema.hash_columns]
        for it in list(out_items) + [f[0] for f in stmt.where]:
            if isinstance(it, P.TokenRef) \
                    and list(it.columns) != hash_col_names:
                raise StatusError(Status.InvalidArgument(
                    f"token() arguments must be the partition key "
                    f"columns {hash_col_names} in order"))
        aggs = _extract_cql_aggregates(out_items)
        if aggs is not None:
            return self._select_aggregate(stmt, aggs, params, cursor)
        if stmt.distinct:
            return self._select_distinct(stmt, params, cursor,
                                         page_size, page_state)
        where = self._bind_where(stmt.where, params, cursor)
        known = {c.name: c.type for c in schema.columns}
        where = self._canon_jsonb_where(where, known)

        # ---- discrete ScanChoices: col IN (...) on a KEY column runs one
        # sub-select per option (ref docdb/scan_choices.cc option seeks)
        key_names = {c.name for c in schema.hash_columns} | \
            {c.name for c in schema.range_columns}
        range_names = {c.name for c in schema.range_columns}
        hash_names = {c.name for c in schema.hash_columns}
        eq_cols = {c for c, op, _v in where if op == "="}
        range_order = [c.name for c in schema.range_columns]
        # ORDER BY validation happens BEFORE any execution-path branch so
        # rejection does not depend on the WHERE shape (CQL: partition
        # key restricted, single direction, clustering-order prefix)
        if stmt.order_by:
            if not hash_names <= eq_cols and not any(
                    op == "in" and c in hash_names for c, op, _v in where):
                raise StatusError(Status.InvalidArgument(
                    "ORDER BY is only supported when the partition key "
                    "is restricted"))
            dirs = {d for _c, d in stmt.order_by}
            if len(dirs) > 1:
                raise StatusError(Status.InvalidArgument(
                    "ORDER BY must use a single direction over the "
                    "clustering order"))
            want = [c for c, _d in stmt.order_by]
            if want != range_order[: len(want)]:
                raise StatusError(Status.InvalidArgument(
                    f"ORDER BY must follow the clustering key order "
                    f"{range_order}"))
        for i, (c, op, v) in enumerate(where):
            if op == "in" and c in key_names:
                if stmt.order_by:
                    # ordered results: take the scan path (IN becomes a
                    # residual filter) so the reversal logic applies once
                    continue
                # only worthwhile when every sub-select still reaches a
                # key prefix — with the hash key unbound, N sub-selects
                # would be N full scans where ONE scan with the IN as a
                # residual filter suffices
                if not hash_names <= (eq_cols | {c}):
                    continue
                # IN is a SET: duplicates must not duplicate rows
                options = list(dict.fromkeys(v))
                if c in range_names:
                    # rows come back in clustering order — option order
                    # must follow it or LIMIT keeps the wrong rows.  That
                    # only holds when every clustering column BEFORE the
                    # IN column is equality-bound: otherwise the per-
                    # option concatenation orders by (c, earlier cols)
                    # instead of clustering order (real CQL rejects such
                    # restrictions outright).  Unsortable option types
                    # fall back to a single residual-filter scan for the
                    # same reason (ADVICE r3).
                    if any(rc not in eq_cols
                           for rc in range_order[:range_order.index(c)]):
                        continue
                    try:
                        options = sorted(options)
                    except TypeError:
                        continue
                merged = ResultSet(columns=[], types=[], source=None)
                limit = stmt.limit
                for option in options:
                    # sub-select built from ALREADY-BOUND pieces (markers
                    # were consumed above; re-binding would misalign)
                    sub = P.Select(stmt.keyspace, stmt.table, out_items,
                                   where=[w for j, w in enumerate(where)
                                          if j != i] + [(c, "=", option)],
                                   limit=limit)
                    rs = self._select(sub, (), [0])
                    merged.columns, merged.types = rs.columns, rs.types
                    merged.source = rs.source
                    merged.rows.extend(rs.rows)
                    if limit is not None:
                        limit -= len(rs.rows)
                        if limit <= 0:
                            break
                return merged
        rs = ResultSet(columns=[self._item_label(i) for i in out_items],
                       types=[self._item_type(i, known) for i in out_items],
                       source=(table.namespace, table.name))
        item_fns = [self._compile_item(i, known) for i in out_items]
        dk, residual = self._doc_key_from_where(table, where)
        full_key = (dk is not None
                    and len(dk.range_components)
                    == schema.num_range_key_columns)
        if full_key:
            row = self._client.read_row(table, dk)
            if row is not None:
                d = self._row_dict(schema, row)
                if self._match(d, residual):
                    rs.rows.append([f(d, row) for f in item_fns])
            return rs
        ps = _decode_page_state(page_state) if page_state else None
        scan_state: dict = {}
        pageable = False
        if dk is not None:
            # Full hash key: single-partition prefix scan on the owning
            # tablet (ref ScanChoices hashed-key scan), not a table scan.
            prefix = DocKey(hash_components=dk.hash_components,
                            range_components=dk.range_components).encode()
            prefix = prefix[:-1]  # open the range group
            lo, hi = self._range_scan_bounds(schema, dk, prefix, residual)
            if ps:
                lo = max(lo, ps[0])
            rows = self._client.scan_key_range(
                table, table.partition_key_for(dk), lo, hi,
                read_ht=HybridTime(ps[2]) if ps else None,
                filters=self._wire_filters(schema, residual),
                scan_state=scan_state)
            pageable = True
        else:
            # No key prefix: try a readable secondary index on an equality
            # predicate before falling back to the full scan.  A resume
            # token forces the scan path: the first page came from a scan
            # (tokens are only issued on pageable paths), and switching to
            # an index that became readable between pages would restart
            # the result set (duplicates) and ignore the pinned snapshot.
            picked = None if ps else IM.choose_index(table, residual)
            if picked is not None:
                idx, value, residual = picked
                ks = self._resolve_ks(stmt.keyspace)
                idx_table = self._table(ks, idx.index_name)
                rows = IM.index_lookup(self._client, table, idx_table,
                                       idx, value)
            else:
                rows = self._client.scan(
                    table, read_ht=HybridTime(ps[2]) if ps else None,
                    filters=self._wire_filters(schema, residual),
                    start_cursor=ps[1] if ps else b"",
                    start_lower=ps[0] if ps else b"",
                    scan_state=scan_state)
                pageable = True
        # ---- ORDER BY clustering columns (CQL: only with the partition
        # key restricted; rows already stream in clustering ASC order, so
        # ASC is a no-op and DESC materializes the partition and
        # reverses — ref: sem analyzer order-by checks + reverse scans)
        if stmt.order_by:
            if {d for _c, d in stmt.order_by} == {True}:
                # DESC: collect the partition's matching rows, reverse;
                # no paging token (the resume cursor is ascending-only)
                collected = []
                for row in rows:
                    d = self._row_dict(schema, row)
                    if tuple(d[c.name] for c in schema.hash_columns) !=                             dk.hash_components:
                        continue
                    if not self._match(d, residual):
                        continue
                    collected.append((d, row))
                collected.reverse()
                budget = ps[3] if ps else stmt.limit
                for d, row in collected:
                    rs.rows.append([f(d, row) for f in item_fns])
                    if budget is not None and len(rs.rows) >= budget:
                        break
                return rs
        # LIMIT budget spans pages: the token carries what is still owed
        remaining = ps[3] if ps else stmt.limit
        count = 0
        rows_it = iter(rows)
        for row in rows_it:
            d = self._row_dict(schema, row)
            if dk is not None and tuple(
                    d[c.name] for c in schema.hash_columns) != \
                    dk.hash_components:
                continue
            if not self._match(d, residual):
                continue
            rs.rows.append([f(d, row) for f in item_fns])
            count += 1
            if remaining is not None and count >= remaining:
                break
            if pageable and page_size is not None and count >= page_size:
                # peek before issuing a token: an exactly-exhausted scan
                # must report "no more pages", not charge the client one
                # extra round trip for an empty final page
                if next(rows_it, None) is not None:
                    rs.paging_state = _encode_page_state(
                        row.doc_key.encode() + b"\xff",
                        table.partition_key_for(row.doc_key),
                        scan_state.get("read_ht", 0),
                        None if remaining is None else remaining - count)
                break
        return rs

    # predicate value classes whose doc-key encoding shares the column's
    # type tag — cross-tag bounds would compare different tag bytes and
    # silently exclude every row (e.g. a float literal on a bigint column)
    _BOUND_TYPES = {
        DataType.INT32: int, DataType.INT64: int,
        DataType.FLOAT: float, DataType.DOUBLE: float,
        DataType.STRING: str, DataType.BINARY: bytes,
        DataType.TIMESTAMP: int,
    }

    @classmethod
    def _bound_type_ok(cls, col_type, v) -> bool:
        want = cls._BOUND_TYPES.get(col_type)
        return want is not None and isinstance(v, want) \
            and not isinstance(v, bool)

    @staticmethod
    def _range_scan_bounds(schema, dk, prefix: bytes, residual) -> tuple:
        """Hybrid ScanChoices: inequality predicates on the first UNBOUND
        clustering column tighten the partition scan's byte range instead
        of filtering after a full-partition read (ref
        docdb/scan_choices.cc range bounds). Component encoding is
        order-preserving, so prefix+encode(v) bounds are exact; the
        predicates stay in the residual (bounds prune, the filter
        decides), so edge inclusivity cannot produce wrong rows."""
        from yugabyte_tpu.docdb.doc_key import PrimitiveValue
        lo, hi = prefix, prefix + b"\xff"
        bound_n = len(dk.range_components)
        if bound_n >= len(schema.range_columns):
            return lo, hi
        nxt_col = schema.range_columns[bound_n]
        nxt = nxt_col.name
        for c, op, v in residual:
            if c != nxt or op not in ("<", "<=", ">", ">="):
                continue
            if not QLProcessor._bound_type_ok(nxt_col.type, v):
                continue  # cross-type predicate: residual filter decides
            buf = bytearray()
            try:
                PrimitiveValue.encode(v, buf)
            except TypeError:
                continue
            enc = prefix + bytes(buf)
            if op in (">", ">="):
                cand = enc + (b"\xff" if op == ">" else b"")
                if cand > lo:
                    lo = cand
            else:
                cand = enc + (b"\xff" if op == "<=" else b"")
                if cand < hi:
                    hi = cand
        return lo, hi

    # -------------------------------------------------------- system vtables
    # Canonical column orders — the metadata contract is FIXED, not
    # derived from whichever rows happen to match (a zero-row
    # "SELECT * FROM system.peers" must still describe its columns).
    SYSTEM_VTABLES: Dict[Tuple[str, str], List[str]] = {
        ("system", "local"): ["key", "rpc_address", "rpc_port",
                              "data_center", "rack", "cluster_name",
                              "partitioner", "release_version",
                              "cql_version", "tokens"],
        ("system", "peers"): ["peer", "rpc_address", "data_center",
                              "rack", "tokens"],
        ("system_schema", "keyspaces"): ["keyspace_name", "durable_writes"],
        ("system_schema", "tables"): ["keyspace_name", "table_name", "id"],
        ("system_schema", "columns"): ["keyspace_name", "table_name",
                                       "column_name", "kind", "position",
                                       "type"],
    }

    def _system_rows(self, ks: str, table: str,
                     eq: Dict[str, object]) -> List[dict]:
        """Synthesized rows of the system/system_schema virtual tables —
        what every Cassandra driver queries on connect (ref: the master's
        YQLVirtualTable family, master/yql_local_vtable.cc,
        yql_peers_vtable.cc, yql_keyspaces_vtable.cc ...).

        eq: equality predicates pushed into generation — metadata
        refreshes filter by keyspace_name/table_name, and opening every
        table in the cluster to answer them would cost O(tables) master
        round-trips per query.

        This processor IS the CQL endpoint (the reference runs one per
        tserver; this architecture runs one standalone server embedding
        the client), so system.local describes THIS server and
        system.peers is empty — there are no other CQL endpoints."""
        if (ks, table) == ("system", "local"):
            host, port = (self.local_addr if self.local_addr
                          else ("127.0.0.1", 0))
            return [{"key": "local", "rpc_address": host,
                     "rpc_port": int(port),
                     "data_center": "datacenter1", "rack": "rack1",
                     "cluster_name": "ybtpu", "partitioner": "multi-hash",
                     "release_version": "3.9-SNAPSHOT",
                     "cql_version": "3.4.4", "tokens": ["0"]}]
        if (ks, table) == ("system", "peers"):
            return []
        want_ks = eq.get("keyspace_name")
        want_table = eq.get("table_name")
        namespaces = ([want_ks] if want_ks is not None
                      else self._client.list_namespaces())
        if (ks, table) == ("system_schema", "keyspaces"):
            return [{"keyspace_name": n, "durable_writes": True}
                    for n in namespaces]
        if (ks, table) == ("system_schema", "tables"):
            rows = []
            for n in namespaces:
                for t in self._client.list_tables(n):
                    if want_table is not None and t["name"] != want_table:
                        continue
                    rows.append({"keyspace_name": n,
                                 "table_name": t["name"],
                                 "id": t.get("table_id", "")})
            return rows
        if (ks, table) == ("system_schema", "columns"):
            rows = []
            for n in namespaces:
                for t in self._client.list_tables(n):
                    if want_table is not None and t["name"] != want_table:
                        continue
                    try:
                        schema = self._table(n, t["name"]).schema
                    except StatusError:
                        continue
                    hash_names = [c.name for c in schema.hash_columns]
                    range_names = [c.name for c in schema.range_columns]
                    for c in schema.columns:
                        kind = ("partition_key" if c.name in hash_names
                                else "clustering" if c.name in range_names
                                else "regular")
                        rows.append({"keyspace_name": n,
                                     "table_name": t["name"],
                                     "column_name": c.name,
                                     "kind": kind,
                                     "position": (
                                         hash_names.index(c.name)
                                         if kind == "partition_key"
                                         else range_names.index(c.name)
                                         if kind == "clustering" else -1),
                                     "type": c.type.value})
            return rows
        raise StatusError(Status.NotFound(f"table {ks}.{table}"))

    def _select_system(self, ks: str, stmt: P.Select, params: List[object],
                       cursor: List[int]) -> ResultSet:
        if (ks, stmt.table) not in self.SYSTEM_VTABLES:
            raise StatusError(Status.NotFound(f"table {ks}.{stmt.table}"))
        where = self._bind_where(stmt.where, params, cursor)
        eq = {c: v for c, op, v in where if op == "="}
        rows = [r for r in self._system_rows(ks, stmt.table, eq)
                if self._match(r, where)]
        items = stmt.columns or self.SYSTEM_VTABLES[(ks, stmt.table)]
        out_cols = [c if isinstance(c, str) else self._item_label(c)
                    for c in items]
        rs = ResultSet(columns=out_cols, types=[None] * len(out_cols),
                       source=(ks, stmt.table))
        limit = stmt.limit
        for r in rows:
            rs.rows.append([r.get(c) if isinstance(c, str) else None
                            for c in items])
            if limit is not None and len(rs.rows) >= limit:
                break
        return rs

    def _run_transaction(self, stmt: P.Transaction,
                         params: List[object]) -> ResultSet:
        """ref executor.cc transactional block execution + retry."""
        cursor = [0]
        for s in stmt.statements:
            if getattr(s, "if_not_exists", False) \
                    or getattr(s, "if_exists", False) \
                    or getattr(s, "conditions", None):
                # conditional DML inside a transaction block would need
                # per-statement [applied] results and condition reads at
                # the block's snapshot — reject loudly rather than apply
                # unconditionally (the reference likewise restricts LWT
                # in batches)
                raise StatusError(Status.NotSupported(
                    "conditional DML (IF ...) inside BEGIN TRANSACTION"))
        decoded = [self._dml_to_op(s, params, cursor)
                   for s in stmt.statements]
        deadline = time.monotonic() + 30
        while True:
            txn = self._txn_manager.begin()
            try:
                for table, op in decoded:
                    IM.txn_write_with_indexes(
                        txn, table, op,
                        lambda name, _t=table: self._table(
                            _t.namespace, name))
                txn.commit()
                return ResultSet()
            except TransactionError:
                txn.abort()
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
            except BaseException:
                # Non-conflict failure: abort, or the still-heartbeating
                # txn would pin its intents indefinitely.
                txn.abort()
                raise
