"""TabletPeer: a replicated tablet = Tablet + RaftConsensus + WAL.

Capability parity with the reference (ref: src/yb/tablet/tablet_peer.h:129 —
glue between Tablet, RaftConsensus and the Log; write submission
tablet_peer.cc:638 `WriteAsync`/:655 `Submit`; bootstrap = WAL replay,
ref tablet/tablet_bootstrap.cc:195 `ReplayState` and
`Tablet::MaxPersistentOpId` tablet.cc:2931).

Key flows:
- Leader write: Tablet.write -> RaftWriteContext.submit -> raft.replicate
  (WAL append + majority ack + in-order apply) -> returns op id. The apply
  callback feeds Tablet.apply_write_batch on every replica.
- Follower safety: writes are rejected with NotLeader; reads serve at the
  leader's propagated safe time (ref mvcc.h:93).
- Bootstrap: storage frontiers tell how far the DBs persisted; WAL entries
  above that (up to the durable committed floor) replay into the tablet,
  the rest stay pending in Raft until a leader commits or truncates them.
- Transport addressing: each peer of each tablet's Raft group registers as
  "<server_id>/<tablet_id>" so one fabric serves many tablets per server
  (the reference routes consensus RPCs by tablet id the same way).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import List, Optional, Sequence, Tuple

from yugabyte_tpu.common.hybrid_time import HybridClock, HybridTime
from yugabyte_tpu.common.schema import Schema
from yugabyte_tpu.consensus.log import Log, LogReader
from yugabyte_tpu.consensus.raft import (
    OP_SNAPSHOT, OP_SPLIT, OP_UPDATE_TXN, OP_WRITE, NotLeader,
    OperationOutcomeUnknown, RaftConfig, RaftConsensus, ReplicateMsg,
    ReplicationTimedOut, Role)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.tablet.tablet import Tablet, TabletOptions

flags.define_flag(
    "follower_read_vouch_ttl_s", 900.0,
    "a digest-exchange vouch lets this replica serve follower reads for "
    "this long; must outlast the exchange cadence (scrub_interval_s) so "
    "a healthy follower stays continuously vouched, while a replica the "
    "exchange stops vouching for ages out")

# Tablet peer states (the reference's RaftGroupStatePB subset that matters
# for failure containment, ref tablet/metadata.proto + tablet_peer.cc
# state gating): RUNNING serves normally; FAILED rejects writes retryably
# while reads drain, is reported via heartbeat so the master re-replicates,
# and recovers via retry_background_work / re-bootstrap.
STATE_RUNNING = "RUNNING"
STATE_FAILED = "FAILED"


def encode_write_batch(kv_items: Sequence[Tuple],
                       target_intents: bool = False,
                       request: Optional[Tuple[bytes, int]] = None) -> bytes:
    """Leading flag byte routes the batch: bit0 -> intents DB (the reference
    splits these into separate WriteBatch sections, ref tablet.cc:1198
    ApplyKeyValueRowOperations); bit1 -> every entry carries a u64 hybrid
    time override (0 = none; index backfill writes at the backfill read
    time, ref tablet.cc:2088); bit2 -> a (client_id[16], request_id u64)
    retryable-request tag trails the entries (exactly-once dedup, ref
    consensus/retryable_requests.cc — replicated WITH the data so every
    replica rebuilds the registry). Items are (key, value) or
    (key, value, ht)."""
    has_ht = any(len(it) == 3 and it[2] for it in kv_items)
    flag = ((1 if target_intents else 0) | (2 if has_ht else 0)
            | (4 if request is not None else 0))
    out = [bytes([flag]), struct.pack("<I", len(kv_items))]
    for it in kv_items:
        k, v = it[0], it[1]
        out.append(struct.pack("<I", len(k)))
        out.append(k)
        out.append(struct.pack("<I", len(v)))
        out.append(v)
        if has_ht:
            out.append(struct.pack(
                "<Q", it[2] if len(it) == 3 and it[2] else 0))
    if request is not None:
        cid, rid = request
        out.append(cid[:16].ljust(16, b"\x00"))
        out.append(struct.pack("<Q", rid))
    return b"".join(out)


def decode_write_batch(payload: bytes
                       ) -> Tuple[List[Tuple], bool,
                                  Optional[Tuple[bytes, int]]]:
    """Inverse of encode_write_batch; items come back as (key, value) or
    (key, value, ht_override), plus the retryable-request tag if present."""
    flag = payload[0]
    target_intents = bool(flag & 1)
    has_ht = bool(flag & 2)
    (n,) = struct.unpack_from("<I", payload, 1)
    off = 5
    pairs = []
    for _ in range(n):
        (kl,) = struct.unpack_from("<I", payload, off)
        off += 4
        k = payload[off:off + kl]
        off += kl
        (vl,) = struct.unpack_from("<I", payload, off)
        off += 4
        v = payload[off:off + vl]
        off += vl
        if has_ht:
            (ht,) = struct.unpack_from("<Q", payload, off)
            off += 8
            pairs.append((k, v, ht) if ht else (k, v))
        else:
            pairs.append((k, v))
    request = None
    if flag & 4:
        cid = payload[off: off + 16]
        (rid,) = struct.unpack_from("<Q", payload, off + 16)
        request = (cid, rid)
    return pairs, target_intents, request


class RaftWriteContext:
    """The consensus seam Tablet.write submits through (replaces
    LocalConsensusContext once a TabletPeer owns the tablet)."""

    def __init__(self, peer: "TabletPeer"):
        self._peer = peer

    def submit(self, kv_pairs, ht: HybridTime, timeout_s: float = 30.0,
               target_intents: bool = False, request=None) -> Tuple[int, int]:
        payload = encode_write_batch(kv_pairs, target_intents,
                                     request=request)
        try:
            return self._peer.raft.replicate(OP_WRITE, ht.value, payload,
                                             timeout_s=timeout_s)
        except ReplicationTimedOut as e:
            # The entry may still commit: MVCC must keep holding safe time
            # at ht until the fate settles, then resolve the registration.
            # The retryable-request stays in-flight until the fate settles
            # too — a concurrent retry must not slip past the dedup check.
            mvcc = self._peer.tablet.mvcc
            retry_reg = self._peer.tablet.retryable

            def on_aborted():
                mvcc.aborted(ht)
                if request is not None:
                    retry_reg.failed(*request)

            self._peer.raft.watch_fate(
                e.op_id,
                on_committed=lambda: mvcc.replicated(ht),
                on_aborted=on_aborted)
            raise OperationOutcomeUnknown(str(e)) from e


def peer_address(server_id: str, tablet_id: str) -> str:
    return f"{server_id}/{tablet_id}"


class TabletPeer:
    def __init__(self, tablet_id: str, data_dir: str, schema: Schema,
                 server_id: str, server_ids: Sequence[str], transport,
                 clock: Optional[HybridClock] = None,
                 options: Optional[TabletOptions] = None,
                 metrics=None):
        self.tablet_id = tablet_id
        self.server_id = server_id
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.clock = clock or HybridClock()
        self.tablet = Tablet(tablet_id, data_dir, schema, clock=self.clock,
                             options=options, metrics=metrics)
        self.log = Log(os.path.join(data_dir, "wal"))
        # WAL-backlog arm of the write-pressure state machine: appends
        # queued faster than fsync drains them delay, then shed, writes
        self.tablet.admission.bind_wal(self.log.backlog)
        config = RaftConfig(
            peer_id=peer_address(server_id, tablet_id),
            peer_ids=tuple(peer_address(s, tablet_id) for s in server_ids))
        self.raft = RaftConsensus(
            config, self.log, transport,
            apply_cb=self._apply_replicated,
            meta_path=os.path.join(data_dir, "cmeta.json"),
            safe_time_provider=lambda: self.tablet.mvcc.peek_safe_time().value,
            on_propagated_safe_time=self._on_propagated_safe_time,
            on_role_change=self._on_role_change,
            clock=self.clock,
            on_append_cb=self._on_entry_appended)
        # Registration is DEFERRED to start(), after bootstrap: serving
        # AppendEntries while bootstrap replays lets the leader's catch-up
        # race the replay — set_bootstrap_state then jumps last_applied
        # past entries the racing apply loop never applied, permanently
        # losing a window of rows on this replica (found by the
        # linked-list churn harness; ref: the reference only serves
        # consensus once the tablet reaches RUNNING state,
        # tablet_peer.cc state gating).
        self._transport = transport
        self.tablet.consensus = RaftWriteContext(self)
        self.tablet.mvcc.set_leader_mode(False)
        # Failure containment: a background error in either DB or a sealed
        # WAL parks this peer in FAILED (ref tablet FAILED state,
        # tablet.cc MarkTabletFailed).
        self.state = STATE_RUNNING
        self.failed_status: Optional[Status] = None
        # data-corruption failure (scrub / read-path CRC mismatch /
        # digest divergence): in-place recovery is impossible — the
        # heartbeat reports it and the master rebuilds this replica from
        # a healthy peer (remote bootstrap in place)
        self.failed_corrupt = False
        # last at-rest scrub of this replica (wall ts + totals), set by
        # the ScrubTabletsOp; {} until the first scrub
        self.scrub_state: dict = {}
        # Follower-read gate (ROADMAP item 1 safety rail): a follower may
        # serve bounded-staleness reads ONLY while it holds a live vouch
        # from the leader's cross-replica digest exchange (PR 8) — the
        # exchange proved this replica's resolved rows match the
        # leader's. 0.0 = never vouched. monotonic deadline.
        self._vouched_until = 0.0
        self._vouch_read_ht = 0  # read_ht the vouching digest was taken at
        for db in (self.tablet.regular_db, self.tablet.intents_db):
            db.on_background_error = self._on_storage_error
        self.log.on_io_error = self._on_log_error
        # Split hook: the tablet manager creates the child tablets when the
        # SPLIT op applies (deterministically on every replica, including
        # WAL replay after restart — child creation is idempotent).
        self.on_split = lambda info: None

    # ------------------------------------------------------------ bootstrap
    def bootstrap(self) -> int:
        """Replay WAL into the tablet (ref tablet_bootstrap.cc). Returns the
        number of entries replayed."""
        frontiers = [db.versions.flushed_frontier.op_id_max[1]
                     for db in (self.tablet.regular_db, self.tablet.intents_db)
                     if db.versions.flushed_frontier is not None]
        flushed_min = min(frontiers) if frontiers else 0
        replay_from = flushed_min + 1
        replayed = 0
        max_ht = 0
        applied_up_to = flushed_min
        # Flushed storage implies those entries were committed; the floor
        # may exceed the (non-fsynced) one recovered from metadata.
        committed_floor = max(self.raft.commit_index, flushed_min)
        for entry in LogReader(self.log.wal_dir).read_all():
            msg = ReplicateMsg.from_log_entry(entry)
            if msg.index < replay_from:
                # already flushed into storage — not replayed, but its
                # retryable-request tag must still resolve to 'replicated'
                # or a post-restart retry would double-apply after the
                # in-flight expiry (dedup must survive restart-after-flush)
                if msg.op_type == OP_WRITE and msg.payload \
                        and msg.payload[0] & 4:
                    cid = msg.payload[-24:-8]
                    (rid,) = struct.unpack("<Q", msg.payload[-8:])
                    self.tablet.retryable.replicated(cid, rid, msg.ht_value)
                continue
            if msg.index > committed_floor:
                break  # pending tail: Raft decides its fate later
            self._apply_replicated(msg)
            applied_up_to = msg.index
            replayed += 1
            max_ht = max(max_ht, msg.ht_value)
        # report what was ACTUALLY applied (flushed state + replay), never
        # the aspirational floor: claiming more would mark unapplied
        # entries applied and lose their rows on this replica forever
        self.raft.set_bootstrap_state(applied_up_to)
        if max_ht:
            ht = HybridTime(max_ht)
            self.clock.update(ht)
            self.tablet.mvcc.set_last_replicated(ht)
        TRACE("bootstrap %s: replayed %d ops from index %d",
              self.tablet_id, replayed, replay_from)
        return replayed

    def start(self, election_timer: bool = True) -> "TabletPeer":
        self.bootstrap()
        # only NOW serve consensus traffic (see __init__: registering
        # before bootstrap races leader catch-up against WAL replay)
        self._transport.register(self.raft.config.peer_id, self.raft)
        self.raft.start(election_timer=election_timer)
        return self

    # ------------------------------------------------------ failure state
    def _on_storage_error(self, status: Status) -> None:
        self.mark_failed(status)

    def _on_log_error(self, exc: Exception) -> None:
        self.mark_failed(Status.IoError(
            f"WAL append failed on {self.tablet_id}: {exc}"))

    def mark_failed(self, status: Status) -> None:
        """Transition to FAILED: writes reject retryably, reads drain, the
        next heartbeat reports the state so the master can re-replicate.
        In-flight background compactions (including the device-offload
        pipeline) are cancelled at their next stage boundary. A
        CORRUPTION status additionally marks the replica
        ``failed_corrupt``: its data is bad, so recovery is a rebuild
        from a healthy peer, never an in-place retry."""
        from yugabyte_tpu.utils.status import Code
        if status.code == Code.CORRUPTION:
            # set even when already FAILED: corruption discovered under
            # an I/O park upgrades the required recovery to a rebuild
            self.failed_corrupt = True
        if self.state == STATE_FAILED:
            return
        self.state = STATE_FAILED
        self.failed_status = status
        # a parked replica's data is suspect by definition: drop any
        # follower-read license it still holds
        self._vouched_until = 0.0
        self.tablet.cancel_background_work(
            f"tablet {self.tablet_id} FAILED: {status}")
        TRACE("tablet %s FAILED: %s", self.tablet_id, status)

    def _check_not_failed(self) -> None:
        if self.state == STATE_FAILED:
            err = StatusError(Status.ServiceUnavailable(
                f"tablet {self.tablet_id} is in FAILED state "
                f"({self.failed_status}); retry another replica"))
            err.extra = {"tablet_failed": True}
            raise err

    def try_recover(self) -> bool:
        """In-place recovery from DB background errors (driven by the
        maintenance manager's capped-backoff retry). A sealed WAL cannot
        recover in place — its torn tail needs the bootstrap replay rule —
        so those peers wait for TSTabletManager.recover_failed_tablet.
        Returns True when the peer is RUNNING again."""
        if self.state != STATE_FAILED:
            return True
        if self.failed_corrupt:
            # lost/diverged bytes cannot be retried back into existence:
            # stay parked until the master rebuilds this replica from a
            # healthy peer (load_balancer in-place remote bootstrap)
            return False
        if self.log.io_error is not None:
            return False
        for db in (self.tablet.regular_db, self.tablet.intents_db):
            if not db.retry_background_work():
                return False
        self.state = STATE_RUNNING
        self.failed_status = None
        TRACE("tablet %s recovered from background error", self.tablet_id)
        return True

    def _on_entry_appended(self, msg: ReplicateMsg) -> None:
        """Log-append hook (every replica, incl. recovery): pre-register the
        write's retryable-request tag as in-flight, so a retry hitting a
        new leader in the committed-but-unapplied window is pushed back
        instead of double-applied (ref retryable_requests.cc registering
        at replication time)."""
        if msg.op_type != OP_WRITE or not msg.payload:
            return
        if msg.payload[0] & 4:
            cid = msg.payload[-24:-8]
            (rid,) = struct.unpack("<Q", msg.payload[-8:])
            self.tablet.retryable.track_appended(cid, rid)

    # ---------------------------------------------------------------- apply
    def _apply_replicated(self, msg: ReplicateMsg) -> None:
        if msg.op_type == OP_WRITE:
            kv_pairs, target_intents, request = decode_write_batch(
                msg.payload)
            ht = HybridTime(msg.ht_value)
            if target_intents:
                self.tablet.apply_intent_batch(kv_pairs, ht, msg.op_id)
            else:
                self.tablet.apply_write_batch(kv_pairs, ht, msg.op_id)
            if request is not None:
                # every replica (and WAL replay) rebuilds the dedup
                # registry from the replicated payload
                self.tablet.retryable.replicated(request[0], request[1],
                                                 msg.ht_value)
            if not self.raft.is_leader():
                # Followers advance replication watermark directly; the
                # leader's MvccManager drains via replicated() in write().
                self.clock.update(ht)
                self.tablet.mvcc.set_last_replicated(ht)
        elif msg.op_type == OP_UPDATE_TXN:
            import json as _json
            info = _json.loads(msg.payload)
            self.tablet.apply_txn_update(
                info["action"], bytes.fromhex(info["txn_id"]),
                info.get("commit_ht") or 0, msg.ht_value, msg.op_id)
        elif msg.op_type == OP_SNAPSHOT:
            # Deterministic: every replica checkpoints the same applied
            # prefix (ref snapshot_coordinator raft-driven snapshots).
            import json as _json
            self.tablet.create_snapshot(
                _json.loads(msg.payload)["snapshot_id"])
        elif msg.op_type == OP_SPLIT:
            # Applied at the same log position on every replica, after all
            # preceding writes and before nothing (the parent rejects writes
            # once the split is appended) — so the parent state each replica
            # snapshots into the children is identical (ref
            # tablet/operations/split_operation.cc).
            import json as _json
            info = _json.loads(msg.payload)
            self.tablet.split_children = tuple(info["children"])
            self.on_split(info)

    def submit_snapshot(self, snapshot_id: str,
                        snapshot_ht_value: int = 0,
                        timeout_s: float = 60.0):
        """Leader: replicate a snapshot barrier. When the master supplies a
        cluster-wide snapshot hybrid time, the leader first waits for
        SafeTime >= snapshot_ht so every write visible at that time is in
        the log BEFORE the barrier — all tablets then restore consistently
        at the same point in time (ref snapshot_coordinator anchoring
        snapshots to one hybrid time)."""
        import json as _json
        if not self.raft.is_leader():
            raise NotLeader(self.raft.leader_hint())
        if snapshot_ht_value:
            self.clock.update(HybridTime(snapshot_ht_value))
            self.tablet.mvcc.safe_time(
                min_allowed=HybridTime(snapshot_ht_value),
                timeout_s=timeout_s)
        payload = _json.dumps({"snapshot_id": snapshot_id,
                               "snapshot_ht": snapshot_ht_value}).encode()
        return self.raft.replicate(OP_SNAPSHOT, self.clock.now().value,
                                   payload, timeout_s=timeout_s)

    def submit_split(self, child_ids, split_partition_key: bytes,
                     timeout_s: float = 30.0):
        """Leader: replicate the split point + child ids through Raft
        (ref tablet/operations/split_operation.h:38). Writes are gated and
        drained FIRST so the SPLIT entry is the last write-affecting entry
        in the parent's log."""
        import json as _json
        payload = _json.dumps({
            "children": list(child_ids),
            "split_partition_key": split_partition_key.hex(),
        }).encode()
        self.tablet.block_writes()
        try:
            return self.raft.replicate(OP_SPLIT, self.clock.now().value,
                                       payload, timeout_s=timeout_s)
        except ReplicationTimedOut as e:
            # Fate unknown: the SPLIT may still commit, so writes MUST stay
            # blocked (an acked write appended after a committing SPLIT
            # would exist only in the soon-retired parent). Unblock only if
            # the entry is eventually overwritten.
            self.raft.watch_fate(
                e.op_id,
                on_committed=lambda: None,  # apply sets split_children
                on_aborted=self.tablet.unblock_writes)
            raise
        except BaseException:
            # Entry definitively not in the log (NotLeader before append)
            # or overwritten (ReplicationAborted): safe to resume writes.
            self.tablet.unblock_writes()
            raise

    def _on_propagated_safe_time(self, ht_value: int) -> None:
        ht = HybridTime(ht_value)
        self.clock.update(ht)
        self.tablet.mvcc.set_propagated_safe_time(ht)

    def _on_role_change(self, role: Role) -> None:
        self.tablet.mvcc.set_leader_mode(role == Role.LEADER)

    # ---------------------------------------------------------------- reads
    def check_leader_lease(self, timeout_s: float = 5.0) -> None:
        """Wait for a majority-acked lease before serving a consistent read
        (the reference blocks on the ht lease the same way, ref
        raft_consensus WaitForLeaderLeaseImprecise)."""
        deadline = time.monotonic() + timeout_s
        while True:
            if not self.raft.is_leader():
                raise NotLeader(self.raft.leader_hint())
            if self.raft.has_leader_lease() and self.raft.leader_ready():
                return
            if time.monotonic() >= deadline:
                raise NotLeader(self.raft.leader_hint())
            time.sleep(0.002)

    # ----------------------------------------------- follower-read vouching
    def grant_vouch(self, read_ht_value: int = 0) -> None:
        """The leader's digest exchange verified this replica's resolved
        rows match its own: license follower reads for the vouch TTL
        (re-granted every clean exchange round, so a replica that starts
        diverging ages out even before the strike path FAILs it)."""
        from yugabyte_tpu.utils.metrics import serve_path_metrics
        self._vouched_until = time.monotonic() + flags.get_flag(
            "follower_read_vouch_ttl_s")
        self._vouch_read_ht = max(self._vouch_read_ht, read_ht_value)
        serve_path_metrics().counter(
            "follower_read_vouches_total",
            "digest-exchange vouches granted to this server's "
            "replicas").increment()

    def revoke_vouch(self) -> None:
        self._vouched_until = 0.0

    def is_vouched(self) -> bool:
        return time.monotonic() < self._vouched_until

    def _check_follower_read_allowed(self) -> None:
        """A follower without a live digest vouch must NOT serve reads —
        push the client to another replica (retryably) instead of
        answering from state nobody has cross-checked. A FAILED replica
        never serves regardless of any vouch it still holds."""
        from yugabyte_tpu.utils.metrics import serve_path_metrics
        self._check_not_failed()
        m = serve_path_metrics()
        if not self.is_vouched():
            m.counter(
                "follower_read_unvouched_rejects_total",
                "follower reads refused because the replica holds no "
                "live digest vouch").increment()
            err = StatusError(Status.ServiceUnavailable(
                f"replica {self.server_id}/{self.tablet_id} holds no "
                f"live digest vouch; read from another replica"))
            err.extra = {"follower_unvouched": True}
            raise err
        m.counter("follower_reads_total",
                  "reads served by a vouched follower replica").increment()

    def _follower_wait_safe_time(self, read_ht: HybridTime,
                                 timeout_s: float = 1.0) -> None:
        """Same repeatable-read guarantee as the leader path — but bounded
        SHORT: a follower whose propagated safe time lags the (already
        stale) read point answers retryably so the client's replica walk
        moves on, instead of pinning the RPC on a 10s MVCC wait."""
        try:
            self.tablet.mvcc.safe_time(min_allowed=read_ht,
                                       timeout_s=timeout_s)
        except TimeoutError as e:
            err = StatusError(Status.ServiceUnavailable(
                f"replica {self.server_id}/{self.tablet_id} safe time "
                f"behind read point; read from another replica"))
            err.extra = {"follower_lagging": True}
            raise err from e

    def read_row(self, doc_key, read_ht: Optional[HybridTime] = None,
                 projection=None, allow_follower: bool = False,
                 txn_id: Optional[bytes] = None):
        if self.raft.is_leader():
            self.check_leader_lease()
            return self.tablet.read_row(doc_key, read_ht, projection,
                                        txn_id=txn_id)
        if not allow_follower:
            raise NotLeader(self.raft.leader_hint())
        self._check_follower_read_allowed()
        if read_ht is not None:
            # Wait (briefly) until the propagated safe time covers the
            # requested read point — same repeatable-read guarantee as
            # the leader path, minus the long stall.
            self._follower_wait_safe_time(read_ht)
            ht = read_ht
        else:
            ht = self.tablet.mvcc.safe_time_for_follower()
        from yugabyte_tpu.docdb.doc_rowwise_iterator import read_row
        return read_row(self.tablet.regular_db, self.tablet.schema, doc_key,
                        ht, projection=projection)

    def multi_read(self, doc_keys, read_ht: Optional[HybridTime] = None,
                   projection=None, allow_follower: bool = False,
                   txn_id: Optional[bytes] = None):
        """Batched point-row reads: read_row's lease/follower rules paid
        ONCE for the whole batch, rows resolved through the tablet's
        batched path (Tablet.multi_read -> DB.multi_get)."""
        if self.raft.is_leader():
            self.check_leader_lease()
            return self.tablet.multi_read(doc_keys, read_ht, projection,
                                          txn_id=txn_id)
        if not allow_follower:
            raise NotLeader(self.raft.leader_hint())
        self._check_follower_read_allowed()
        if read_ht is not None:
            # same repeatable-read guarantee as the follower read_row:
            # bounded wait for propagated safe time to cover the point
            self._follower_wait_safe_time(read_ht)
            ht = read_ht
        else:
            ht = self.tablet.mvcc.safe_time_for_follower()
        return self.tablet.multi_read(doc_keys, ht, projection)

    def write(self, ops, timeout_s: float = 30.0,
              request=None) -> HybridTime:
        self._check_not_failed()
        if not self.raft.is_leader():
            raise NotLeader(self.raft.leader_hint())
        return self.tablet.write(ops, timeout_s=timeout_s, request=request)

    def apply_external_batch(self, kvs, default_ht_value: int) -> HybridTime:
        self._check_not_failed()
        if not self.raft.is_leader():
            raise NotLeader(self.raft.leader_hint())
        return self.tablet.apply_external_batch(kvs, default_ht_value)

    def write_transactional(self, ops, txn_meta,
                            timeout_s: float = 30.0,
                            write_id_base: int = 0) -> HybridTime:
        self._check_not_failed()
        if not self.raft.is_leader():
            raise NotLeader(self.raft.leader_hint())
        return self.tablet.write_transactional(ops, txn_meta,
                                               timeout_s=timeout_s,
                                               write_id_base=write_id_base)

    def submit_txn_update(self, action: str, txn_id: bytes,
                          commit_ht_value: int = 0,
                          timeout_s: float = 30.0):
        """Replicate a transaction resolution through this tablet's Raft
        group (ref transaction_participant.cc apply/cleanup tasks riding
        UpdateTransaction operations)."""
        import json as _json
        if not self.raft.is_leader():
            raise NotLeader(self.raft.leader_hint())
        payload = _json.dumps({"action": action, "txn_id": txn_id.hex(),
                               "commit_ht": commit_ht_value}).encode()
        return self.raft.replicate(OP_UPDATE_TXN, self.clock.now().value,
                                   payload, timeout_s=timeout_s)

    # ----------------------------------------------------------- background
    def wal_anchor(self, assume_flushed: bool = False) -> int:
        """Index below which WAL entries are no longer needed: min of the
        flushed frontiers, lagging-peer watermarks, and CDC retention
        (ref log_anchor_registry).

        assume_flushed: score 'what could a flush release' — skip the
        flushed-frontier component (a flush advances it) but KEEP the
        raft/CDC pins, which a flush cannot move."""
        if assume_flushed:
            anchor = self.raft.observed_state()[1] + 1
        else:
            frontiers = [db.versions.flushed_frontier.op_id_max[1]
                         for db in (self.tablet.regular_db,
                                    self.tablet.intents_db)
                         if db.versions.flushed_frontier is not None]
            anchor = (min(frontiers) + 1) if frontiers else 0
        # Never GC entries a lagging peer still needs (there is no remote
        # bootstrap yet to rebuild it from a snapshot).
        anchor = min(anchor, self.raft.wal_gc_anchor())
        # CDC retention: a consumer's checkpoint pins the WAL — GC'ing
        # unstreamed changes would silently tear the replication stream
        # (ref cdc_min_replicated_index-driven retention)
        cdc_idx = getattr(self, "cdc_retention_index", None)
        if cdc_idx is not None:
            anchor = min(anchor, cdc_idx + 1)
        return anchor

    def gc_wal(self) -> int:
        """Drop WAL segments fully below the current anchor (no flush)."""
        return self.log.gc_up_to(self.wal_anchor())

    def flush_and_gc_wal(self) -> int:
        """Flush both DBs, then drop WAL segments fully below the persisted
        frontier (ref log GC driven by flushed OpId anchors)."""
        self.tablet.flush()
        return self.gc_wal()

    def shutdown(self) -> None:
        self.raft.shutdown()
        self.log.close()
        self.tablet.close()
