"""DocKey / SubDocKey: order-preserving document key encoding.

Capability parity with the reference (ref: src/yb/docdb/doc_key.h:42-82
DocKey, :467 SubDocKey; string zero-encoding per
src/yb/docdb/doc_kv_util.h:95 ZeroEncodeAndAppendStrToKey).

Layout of an encoded SubDocKey (matches the reference's structure):

    [kUInt16Hash][2B big-endian hash]        (hash-partitioned tables only)
    [hashed components]* [kGroupEnd]
    [range components]*  [kGroupEnd]
    [subkeys]*
    [kHybridTime][12-byte descending DocHybridTime]   (see common/hybrid_time.py)

Each component is an order-preserving PrimitiveValue encoding:
  - string: kString + zero-encoded bytes (\\x00 -> \\x00\\x01, terminator \\x00\\x00)
  - int32/int64: tag + big-endian with sign bit flipped
  - double/float: tag + IEEE bits with order-preserving transform
  - bool: kTrue / kFalse tag only;  null: kNullLow tag only
  - column id: kColumnId + 2B big-endian

TPU note: because the hash prefix and all components are big-endian and
order-preserving, the raw key bytes sort with plain memcmp — which is exactly
what the TPU merge kernel does after packing keys into big-endian u32 word
slabs (ops/slabs.py).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

from yugabyte_tpu.common.hybrid_time import DocHybridTime, ENCODED_DOC_HT_SIZE
from yugabyte_tpu.common.partition import hash_column_compound_value
from yugabyte_tpu.docdb.value_type import ValueType

PrimitiveType = Union[None, bool, int, float, str, bytes]

_I32_OFF = 1 << 31
_I64_OFF = 1 << 63


def zero_encode(data: bytes) -> bytes:
    """\\x00 -> \\x00\\x01; terminate with \\x00\\x00 (order-preserving, ref doc_kv_util.h:95)."""
    return data.replace(b"\x00", b"\x00\x01") + b"\x00\x00"


def zero_decode(data: bytes, pos: int) -> Tuple[bytes, int]:
    """Inverse of zero_encode, starting at pos; returns (decoded, new_pos)."""
    out = bytearray()
    n = len(data)
    while pos < n:
        b = data[pos]
        if b == 0:
            if pos + 1 >= n:
                raise ValueError("truncated zero-encoded string")
            nxt = data[pos + 1]
            if nxt == 0:
                return bytes(out), pos + 2
            if nxt == 1:
                out.append(0)
                pos += 2
                continue
            raise ValueError("corrupt zero-encoded string")
        out.append(b)
        pos += 1
    raise ValueError("unterminated zero-encoded string")


class PrimitiveValue:
    """Encode/decode one key component or primitive value payload."""

    @staticmethod
    def encode(v: PrimitiveType, buf: bytearray) -> None:
        if v is None:
            buf.append(ValueType.kNullLow)
        elif v is True:
            buf.append(ValueType.kTrue)
        elif v is False:
            buf.append(ValueType.kFalse)
        elif isinstance(v, int):
            # Always kInt64: a single tag keeps memcmp order == numeric order
            # for ALL ints in a column. (Tagging by magnitude would order any
            # int64-range value after every int32-range value.)
            buf.append(ValueType.kInt64)
            buf += struct.pack(">Q", v + _I64_OFF)
        elif isinstance(v, float):
            buf.append(ValueType.kDouble)
            bits = struct.unpack(">Q", struct.pack(">d", v))[0]
            # Order-preserving float transform: flip sign bit for positives,
            # flip all bits for negatives.
            bits = bits ^ _I64_OFF if not (bits >> 63) else bits ^ 0xFFFFFFFFFFFFFFFF
            buf += struct.pack(">Q", bits)
        elif isinstance(v, str):
            buf.append(ValueType.kString)
            buf += zero_encode(v.encode("utf-8"))
        elif isinstance(v, bytes):
            # Distinct tag so round-trips are type-stable (str stays str,
            # bytes stay bytes). BINARY and STRING are distinct schema types,
            # so they never share a column and relative order is irrelevant.
            buf.append(ValueType.kBinary)
            buf += zero_encode(v)
        else:
            raise TypeError(f"unsupported key component type: {type(v)}")

    @staticmethod
    def encode_column_id(cid: int, buf: bytearray) -> None:
        if cid < 0:
            buf.append(ValueType.kSystemColumnId)
            buf += struct.pack(">H", -cid)
        else:
            buf.append(ValueType.kColumnId)
            buf += struct.pack(">H", cid)

    @staticmethod
    def decode(data: bytes, pos: int) -> Tuple[PrimitiveType, int]:
        tag = data[pos]
        pos += 1
        if tag == ValueType.kNullLow:
            return None, pos
        if tag == ValueType.kTrue:
            return True, pos
        if tag == ValueType.kFalse:
            return False, pos
        if tag == ValueType.kInt32:
            (u,) = struct.unpack_from(">I", data, pos)
            return u - _I32_OFF, pos + 4
        if tag == ValueType.kInt64:
            (u,) = struct.unpack_from(">Q", data, pos)
            return u - _I64_OFF, pos + 8
        if tag == ValueType.kDouble:
            (bits,) = struct.unpack_from(">Q", data, pos)
            bits = bits ^ _I64_OFF if (bits >> 63) else bits ^ 0xFFFFFFFFFFFFFFFF
            return struct.unpack(">d", struct.pack(">Q", bits))[0], pos + 8
        if tag == ValueType.kString:
            raw, pos = zero_decode(data, pos)
            return raw.decode("utf-8"), pos
        if tag == ValueType.kBinary:
            raw, pos = zero_decode(data, pos)
            return raw, pos
        if tag == ValueType.kColumnId:
            (cid,) = struct.unpack_from(">H", data, pos)
            return ("col", cid), pos + 2
        if tag == ValueType.kSystemColumnId:
            (cid,) = struct.unpack_from(">H", data, pos)
            return ("col", -cid), pos + 2
        raise ValueError(f"unknown value tag {tag:#x} at {pos - 1}")


@dataclass(frozen=True)
class DocKey:
    """Primary key of one document: hashed group + range group.

    encode()/hash_code memoize on first use (the instance is frozen, so
    the encoding can never change): the client encodes every key once
    for partition routing and again for the wire/read path, and the
    hash-compound pass was the single hottest client-side line under
    batched load."""

    hash_components: Tuple[PrimitiveType, ...] = ()
    range_components: Tuple[PrimitiveType, ...] = ()
    use_hash: Optional[bool] = None  # default: hash iff hash_components present

    def encode(self) -> bytes:
        cached = self.__dict__.get("_enc")
        if cached is not None:
            return cached
        buf = bytearray()
        use_hash = self.use_hash if self.use_hash is not None else bool(self.hash_components)
        if use_hash:
            hbuf = bytearray()
            for c in self.hash_components:
                PrimitiveValue.encode(c, hbuf)
            hc = hash_column_compound_value(bytes(hbuf))
            object.__setattr__(self, "_hash_code", hc)
            buf.append(ValueType.kUInt16Hash)
            buf += struct.pack(">H", hc)
            buf += hbuf
            buf.append(ValueType.kGroupEnd)
        for c in self.range_components:
            PrimitiveValue.encode(c, buf)
        buf.append(ValueType.kGroupEnd)
        out = bytes(buf)
        object.__setattr__(self, "_enc", out)
        return out

    @property
    def hash_code(self) -> Optional[int]:
        if not self.hash_components:
            return None
        cached = self.__dict__.get("_hash_code")
        if cached is not None:
            return cached
        hbuf = bytearray()
        for c in self.hash_components:
            PrimitiveValue.encode(c, hbuf)
        hc = hash_column_compound_value(bytes(hbuf))
        object.__setattr__(self, "_hash_code", hc)
        return hc

    @staticmethod
    def decode(data: bytes, pos: int = 0) -> Tuple["DocKey", int]:
        hash_components: List[PrimitiveType] = []
        range_components: List[PrimitiveType] = []
        had_hash = False
        if pos < len(data) and data[pos] == ValueType.kUInt16Hash:
            had_hash = True
            pos += 3  # tag + 2-byte hash (recomputable from components)
            while pos < len(data) and data[pos] != ValueType.kGroupEnd:
                v, pos = PrimitiveValue.decode(data, pos)
                hash_components.append(v)
            if pos >= len(data):
                raise ValueError("truncated DocKey: unterminated hashed group")
            pos += 1
        while pos < len(data) and data[pos] != ValueType.kGroupEnd:
            v, pos = PrimitiveValue.decode(data, pos)
            range_components.append(v)
        if pos >= len(data):
            raise ValueError("truncated DocKey: unterminated range group")
        pos += 1  # range kGroupEnd
        return DocKey(tuple(hash_components), tuple(range_components), had_hash), pos


@dataclass(frozen=True)
class SubDocKey:
    """DocKey + subkeys + DocHybridTime: the full versioned KV key.

    (ref: doc_key.h:467). Subkeys address nested fields — for relational rows
    one subkey = the column id; deeper paths serve collections/jsonb.
    """

    doc_key: DocKey
    subkeys: Tuple[PrimitiveType, ...] = ()
    doc_ht: Optional[DocHybridTime] = None

    def encode(self, include_ht: bool = True) -> bytes:
        buf = bytearray(self.doc_key.encode())
        for sk in self.subkeys:
            if isinstance(sk, tuple) and len(sk) == 2 and sk[0] == "col":
                PrimitiveValue.encode_column_id(sk[1], buf)
            else:
                PrimitiveValue.encode(sk, buf)
        if include_ht and self.doc_ht is not None:
            buf.append(ValueType.kHybridTime)
            buf += self.doc_ht.encoded()
        return bytes(buf)

    @staticmethod
    def decode(data: bytes) -> "SubDocKey":
        doc_key, pos = DocKey.decode(data, 0)
        subkeys: List[PrimitiveType] = []
        doc_ht = None
        n = len(data)
        while pos < n:
            if data[pos] == ValueType.kHybridTime:
                doc_ht = DocHybridTime.decode(data[pos + 1: pos + 1 + ENCODED_DOC_HT_SIZE])
                pos += 1 + ENCODED_DOC_HT_SIZE
                break
            v, pos = PrimitiveValue.decode(data, pos)
            subkeys.append(v)
        return SubDocKey(doc_key, tuple(subkeys), doc_ht)


def split_key_and_ht(encoded: bytes) -> Tuple[bytes, Optional[DocHybridTime]]:
    """Split an encoded SubDocKey into (key prefix without HT, DocHybridTime).

    The fixed-width HT encoding makes this O(1) from the end of the key
    (ref: DecodeFromEnd usage, docdb_compaction_filter.cc:123).
    """
    ht_section = 1 + ENCODED_DOC_HT_SIZE
    if len(encoded) >= ht_section and encoded[-ht_section] == ValueType.kHybridTime:
        return encoded[:-ht_section], DocHybridTime.decode(encoded[-ENCODED_DOC_HT_SIZE:])
    return encoded, None
