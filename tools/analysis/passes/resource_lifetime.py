"""resource-lifetime: acquire/release pairing must be exception-safe.

Three resource families, all hot-path and all leak-prone under the PR 3
threading model (exceptions unwind through pipeline stages on worker
threads, so "the happy path releases it" is not a lifetime story):

- HostStagingPool leases: `arr = pool.acquire(shape)` must reach
  `pool.release(arr)` on every path that never handed the buffer to a
  retaining H2D copy — at minimum, an exception between acquire and the
  upload must release (or the pool's warm pinned pages degrade to
  one-shot allocations);
- file/SST handles: `f = get_env().open_append(...)` / `open(...)`
  bound to a local must be closed via `with`, or `close()` from a
  `finally` — an exception path that drops the handle leaks the fd and,
  through FaultInjectionEnv, keeps a torn file undetected;
- tracked locks: a raw `lock.acquire()` statement (outside `with`) must
  be followed by a try/finally whose finalbody releases it.

Rules (lexical, per function):
- binding escapes (stored to an attribute/subscript, returned, yielded,
  or — for handles — passed as an argument to another call): ownership
  transferred, not checked here;
- `with ...` acquisition is safe by construction;
- otherwise: no release at all               -> `unreleased`
             release exists, but no release sits in a `finally` (and
             there is no except-path release mirroring the normal-path
             one)                            -> `leak-on-exception`
- raw lock acquire without try/finally       -> `raw-lock-acquire`

Receiver recognition is name-based (contains 'pool'/'staging' for
leases, 'lock'/'mutex'/'_mu' for locks) plus index-typed locals whose
class resolves to HostStagingPool. Waive deliberate transfers with
`# yblint: disable=resource-lifetime`.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tools.analysis.core import AnalysisPass, FileContext, Finding
from tools.analysis.project_index import ProjectIndex, dotted_name

PASS_NAME = "resource-lifetime"

DEFAULT_DIRS = ("yugabyte_tpu",)
_POOL_HINTS = ("pool", "staging")
_LOCK_HINTS = ("lock", "mutex", "_mu")
_OPEN_METHODS = ("open_append", "open_random", "open_write",
                 "open_sequential")
_POOL_CLASS_SUFFIX = ".HostStagingPool"


def _receiver_leaf(func: ast.AST) -> str:
    """'pool' from pool.acquire / self._pool.acquire; '' otherwise."""
    if not isinstance(func, ast.Attribute):
        return ""
    base = func.value
    if isinstance(base, ast.Name):
        return base.id.lower()
    if isinstance(base, ast.Attribute):
        return base.attr.lower()
    return ""


class _Acquisition:
    __slots__ = ("binding", "kind", "node", "recv")

    def __init__(self, binding: str, kind: str, node: ast.AST, recv: str):
        self.binding = binding   # local name holding the resource
        self.kind = kind         # "lease" | "file"
        self.node = node
        self.recv = recv         # receiver dotted expr ('' for open())


class ResourceLifetimePass(AnalysisPass):
    name = PASS_NAME
    needs_index = True

    def __init__(self, dirs=DEFAULT_DIRS):
        self.dirs = tuple(d.rstrip("/") + "/" for d in dirs)

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.dirs)

    def run(self, ctx: FileContext, index: Optional[ProjectIndex] = None
            ) -> List[Finding]:
        if index is None:
            index = ProjectIndex([ctx])
        out: List[Finding] = []
        for fn in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            out.extend(self._check_function(ctx, index, fn))
        return out

    # ---------------------------------------------------------- collection
    def _is_pool_typed(self, ctx, index: ProjectIndex, fn: ast.AST,
                       recv_root: str) -> bool:
        key = index.key_of(fn)
        fi = index.lookup_function(key)
        if fi is None:
            return False
        t = index.local_types(fi).get(recv_root, "")
        return t.endswith(_POOL_CLASS_SUFFIX)

    def _classify_value(self, ctx, index, fn,
                        value: ast.AST) -> Optional[Tuple[str, str]]:
        """(kind, recv) when `value` acquires a tracked resource."""
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        d = dotted_name(f)
        if d in ("open", "io.open"):
            return ("file", "")
        if isinstance(f, ast.Attribute) and f.attr in _OPEN_METHODS:
            return ("file", dotted_name(f.value))
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            recv = _receiver_leaf(f)
            root = dotted_name(f.value).split(".")[0]
            if any(h in recv for h in _POOL_HINTS) \
                    or self._is_pool_typed(ctx, index, fn, root):
                return ("lease", dotted_name(f.value))
        return None

    def _direct_nodes(self, fn: ast.AST) -> List[ast.AST]:
        out: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    # -------------------------------------------------------------- checks
    def _check_function(self, ctx, index, fn) -> List[Finding]:
        nodes = self._direct_nodes(fn)
        findings: List[Finding] = []
        acquisitions: List[_Acquisition] = []
        for n in nodes:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                cls = self._classify_value(ctx, index, fn, n.value)
                if cls is not None:
                    acquisitions.append(_Acquisition(
                        n.targets[0].id, cls[0], n, cls[1]))
            elif isinstance(n, ast.Expr) and isinstance(n.value, ast.Call):
                findings.extend(self._check_raw_lock(ctx, fn, n.value))
        for acq in acquisitions:
            f = self._check_acquisition(ctx, fn, nodes, acq)
            if f is not None:
                findings.append(f)
        return findings

    def _check_raw_lock(self, ctx, fn, call: ast.Call) -> List[Finding]:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "acquire"
                and not call.args and not call.keywords):
            return []
        recv = _receiver_leaf(f)
        if not any(h in recv for h in _LOCK_HINTS):
            return []
        # exception-safe iff some enclosing-or-following Try has a
        # matching .release() in its finalbody
        recv_d = dotted_name(f.value)
        for n in ast.walk(fn):
            if isinstance(n, ast.Try):
                for fin in n.finalbody:
                    for c in ast.walk(fin):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and c.func.attr == "release" \
                                and dotted_name(c.func.value) == recv_d:
                            return []
        return [ctx.finding(
            self.name, "raw-lock-acquire", call,
            f"raw {recv_d}.acquire() without a try/finally release — "
            f"use `with {recv_d}:` (exception-safe, and what the "
            "lock-rank tracker instruments)")]

    def _check_acquisition(self, ctx, fn, nodes,
                           acq: _Acquisition) -> Optional[Finding]:
        releases: List[ast.AST] = []
        escaped = False
        for n in nodes:
            if getattr(n, "lineno", 0) < acq.node.lineno:
                continue
            if self._is_release(n, acq):
                releases.append(n)
                continue
            if self._escapes(ctx, n, acq):
                escaped = True
                break
        if escaped:
            return None
        if not releases:
            return ctx.finding(
                self.name, "unreleased", acq.node,
                f"{acq.binding!r} ({acq.kind}) acquired but never "
                f"released/closed in {fn.name} and never handed off — "
                "leaks on every path")
        in_finally = any(self._inside_finally(ctx, r, fn)
                         for r in releases)
        in_except = any(self._inside_except(ctx, r, fn)
                        for r in releases)
        on_normal = any(not self._inside_except(ctx, r, fn)
                        for r in releases)
        if in_finally or (in_except and on_normal):
            return None
        return ctx.finding(
            self.name, "leak-on-exception", acq.node,
            f"{acq.binding!r} ({acq.kind}) release is not exception-"
            f"safe in {fn.name}: put it in a `finally` (or mirror it on "
            "the except path) so an unwind between acquire and release "
            "cannot leak it")

    def _is_release(self, n: ast.AST, acq: _Acquisition) -> bool:
        for c in ast.walk(n):
            if not isinstance(c, ast.Call) \
                    or not isinstance(c.func, ast.Attribute):
                continue
            if acq.kind == "lease" and c.func.attr == "release" \
                    and c.args and isinstance(c.args[0], ast.Name) \
                    and c.args[0].id == acq.binding:
                return True
            if acq.kind == "file" and c.func.attr == "close" \
                    and isinstance(c.func.value, ast.Name) \
                    and c.func.value.id == acq.binding:
                return True
        return False

    def _escapes(self, ctx, n: ast.AST, acq: _Acquisition) -> bool:
        for c in ast.walk(n):
            if not (isinstance(c, ast.Name) and c.id == acq.binding
                    and isinstance(c.ctx, ast.Load)):
                continue
            parent = ctx.parent(c)
            # returned / yielded (possibly inside a tuple)
            anc = parent
            while isinstance(anc, (ast.Tuple, ast.List)):
                anc = ctx.parent(anc)
            if isinstance(anc, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True
            # stored through an attribute or container
            if isinstance(anc, ast.Assign) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in anc.targets):
                return True
            # handles passed as an argument transfer ownership
            if acq.kind == "file" and isinstance(parent, ast.Call) \
                    and c in parent.args:
                return True
        return False

    def _inside_finally(self, ctx, node: ast.AST, fn: ast.AST) -> bool:
        for a in ctx.ancestors(node):
            if a is fn:
                return False
            if isinstance(a, ast.Try):
                for fin in a.finalbody:
                    if node is fin or any(node is d
                                          for d in ast.walk(fin)):
                        return True
        return False

    def _inside_except(self, ctx, node: ast.AST, fn: ast.AST) -> bool:
        for a in ctx.ancestors(node):
            if a is fn:
                return False
            if isinstance(a, ast.ExceptHandler):
                return True
        return False
