"""ScanSpec: the pushed-down query fragment a scan carries to storage.

ROADMAP item 5 (query pushdown): the YCQL executor classifies a SELECT's
WHERE conjunction + aggregate list into the device-compilable subset and
threads the result — this ScanSpec — through the scan RPC down to
`ops/scan.py`'s fused filtered/aggregating kernels, so predicate checks
and COUNT/SUM/MIN/MAX reductions happen where the data sits instead of
surfacing every row to host Python (the LSM-OPD compute-where-the-data-
sits argument applied to the query layer).

The compilable subset is deliberately EXACT, never approximate: a
predicate compiles only when the device's encoded-byte comparison is
provably identical to the host path's decoded-Python comparison —
  - integer-family columns (INT32/INT64/TIMESTAMP): every int encodes as
    kInt64 + big-endian offset binary (docdb/doc_key.py), so memcmp
    order == numeric order and byte equality == value equality;
  - BOOL columns: the value IS the tag byte (kFalse=70 < kTrue=84,
    matching Python False < True).
Floats are excluded (the -0.0/NaN corners of IEEE comparison diverge
from the order-preserving byte transform), strings are excluded
(variable width exceeds the fixed value-word stride), collections/jsonb
are excluded (their "value" is a subdocument). Anything outside the
subset falls back to the host path per query, byte/result-identically,
counted by reason (`scan_pushdown_fallback_*_total`).

NULL semantics are mode-exact: the AGGREGATE path implements the CQL
executor's `_match` (a NULL/absent column fails the row for EVERY
operator, `!=` included — there is no per-row re-check downstream of a
scalar), while the ROW-SCAN path implements the wire filter contract
(`common/wire.FILTER_OPS`, what the tserver's host fallback and the
pgsql pushdown evaluate): NULL fails everything EXCEPT `!=`, which it
passes — packed on device as NOT(exists an equal entry). On device the
NULL exclusion is the payload-tag check — a kNullLow payload never
matches a kInt64/kTrue/kFalse tag pair.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from yugabyte_tpu.common.schema import DataType, Schema
from yugabyte_tpu.docdb.doc_key import PrimitiveValue
from yugabyte_tpu.docdb.value_type import ValueType

class PushdownUnsupported(Exception):
    """A compiled ScanSpec hit a storage-side blocker (deep documents,
    missing device, oversized batch, ...): the caller must serve the
    query through the host path. `reason` keys the fallback counter."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


# operators the fused kernel evaluates (op codes are kernel operand data)
PUSHDOWN_OPS = ("=", "!=", "<", "<=", ">", ">=")
OP_CODES = {op: i + 1 for i, op in enumerate(PUSHDOWN_OPS)}  # 0 = inactive

# integer-family column types: stored payloads are kInt64 + 8B biased BE
_INT_TYPES = (DataType.INT32, DataType.INT64, DataType.TIMESTAMP)

AGG_FNS = ("count", "sum", "avg", "min", "max")

# value words per entry staged for pushdown: 3 words = 12 bytes covers
# the widest compilable payload (kInt64 tag + 8 bytes = 9)
VAL_WORDS = 3


@dataclass(frozen=True)
class ColPredicate:
    """One compiled column comparison: `col op literal`."""
    col: str
    cid: int
    op: str
    value: object
    enc: bytes           # encoded payload bytes of the literal
    tag_a: int           # acceptable payload tag byte(s): a stored value
    tag_b: int           # outside {tag_a, tag_b} fails the row (NULLs)


@dataclass(frozen=True)
class AggSpec:
    """One aggregate over the filtered row set. col/cid are None for
    COUNT(*)."""
    fn: str
    col: Optional[str] = None
    cid: Optional[int] = None
    tag_a: int = 0
    tag_b: int = 0


@dataclass(frozen=True)
class ScanSpec:
    """Predicate conjunction + aggregate list the kernels evaluate."""
    predicates: Tuple[ColPredicate, ...] = ()
    aggregates: Tuple[AggSpec, ...] = ()

    @property
    def needs_vals(self) -> bool:
        """True when the dispatch needs the staged value words: any
        column predicate, or any aggregate naming a column (COUNT(col)
        checks the payload tag to exclude NULLs)."""
        return bool(self.predicates) or any(a.cid is not None
                                            for a in self.aggregates)

    @property
    def agg_cids(self) -> Tuple[int, ...]:
        """Distinct aggregated column ids, in first-appearance order."""
        seen: List[int] = []
        for a in self.aggregates:
            if a.cid is not None and a.cid not in seen:
                seen.append(a.cid)
        return tuple(seen)


def _column(schema: Schema, name):
    if not isinstance(name, str):
        return None
    try:
        return schema.column(name)
    except KeyError:
        return None


def _value_tags(col_type: DataType, value) -> Optional[Tuple[int, int]]:
    """(tag_a, tag_b) acceptable payload tags for a literal on a column,
    or None when the (type, literal) pair is outside the subset."""
    if col_type in _INT_TYPES:
        if isinstance(value, bool) or not isinstance(value, int):
            return None
        return (int(ValueType.kInt64), int(ValueType.kInt64))
    if col_type is DataType.BOOL:
        if not isinstance(value, bool):
            return None
        return (int(ValueType.kFalse), int(ValueType.kTrue))
    return None


def encode_literal(value) -> bytes:
    """Encoded DocValue payload bytes of a predicate literal — exactly
    what a stored (non-NULL, non-TTL'd) cell of that value holds."""
    buf = bytearray()
    PrimitiveValue.encode(value, buf)
    return bytes(buf)


def compile_predicate(schema: Schema, col, op: str,
                      value) -> Optional[ColPredicate]:
    """Compile one WHERE triple, or None when outside the subset (wrong
    op, key column, collection/jsonb/float/string column, mistyped or
    NULL literal)."""
    if op not in PUSHDOWN_OPS or value is None:
        return None
    c = _column(schema, col)
    if c is None or c.collection is not None:
        return None
    key_names = {k.name for k in schema.hash_columns} | \
        {k.name for k in schema.range_columns}
    if col in key_names:
        # key components are pushed as encoded byte BOUNDS by the scan
        # planner, not as value predicates (they have no column entry)
        return None
    tags = _value_tags(c.type, value)
    if tags is None:
        return None
    return ColPredicate(col=col, cid=schema.column_id(col), op=op,
                        value=value, enc=encode_literal(value),
                        tag_a=tags[0], tag_b=tags[1])


def compile_aggregate(schema: Schema, fn: str,
                      col: Optional[str]) -> Optional[AggSpec]:
    """Compile one aggregate, or None when outside the subset. SUM/AVG/
    MIN/MAX compile only over integer-family columns (exact byte-column
    sums + biased-limb min/max); COUNT(col) additionally over BOOL."""
    fn = fn.lower()
    if fn not in AGG_FNS:
        return None
    if col is None:
        return AggSpec(fn="count") if fn == "count" else None
    c = _column(schema, col)
    if c is None or c.collection is not None:
        return None
    key_names = {k.name for k in schema.hash_columns} | \
        {k.name for k in schema.range_columns}
    if col in key_names:
        # key components have no column entries to reduce over (and a
        # key is never NULL — the host path answers COUNT(key) exactly)
        return None
    if c.type in _INT_TYPES:
        tags = (int(ValueType.kInt64), int(ValueType.kInt64))
    elif c.type is DataType.BOOL and fn == "count":
        tags = (int(ValueType.kFalse), int(ValueType.kTrue))
    else:
        return None
    return AggSpec(fn=fn, col=col, cid=schema.column_id(col),
                   tag_a=tags[0], tag_b=tags[1])


def compile_filters(schema: Schema, filters: Optional[Sequence[Sequence]],
                    aggregates: Optional[Sequence[Sequence]] = None
                    ) -> Tuple[Optional[ScanSpec], List[List], str]:
    """Classify a wire filter conjunction (+ optional aggregate list)
    into (spec, leftover_filters, reason).

    spec is None — with `reason` naming the first blocker — when nothing
    is pushable, or when aggregates were requested but ANY aggregate or
    ANY filter is outside the subset (an aggregating scan cannot half-
    push: the scalar must be computed over exactly the filtered row
    set). For row scans partial pushdown is fine: leftover_filters are
    evaluated host-side after the fused filter."""
    filters = filters or ()
    preds: List[ColPredicate] = []
    leftover: List[List] = []
    reason = ""
    for f in filters:
        col, op, value = f[0], f[1], f[2]
        p = compile_predicate(schema, col, op, value)
        if p is None:
            leftover.append(list(f))
            reason = reason or ("op" if op not in PUSHDOWN_OPS else "type")
        else:
            preds.append(p)
    if aggregates:
        aggs: List[AggSpec] = []
        for a in aggregates:
            spec = compile_aggregate(schema, a[0], a[1])
            if spec is None:
                return None, [list(f) for f in filters], "agg_type"
            aggs.append(spec)
        if leftover:
            return None, [list(f) for f in filters], reason or "type"
        return ScanSpec(tuple(preds), tuple(aggs)), [], ""
    if not preds:
        return None, leftover, reason or "no_predicates"
    return ScanSpec(tuple(preds)), leftover, ""


def combine_agg_partials(partials: Sequence[dict]) -> dict:
    """Merge per-tablet aggregate partials (disjoint row sets): counts
    and sums add, mins/maxes reduce, None means "no qualifying rows"."""
    out = {"rows": 0, "cols": {}}
    for p in partials:
        out["rows"] += int(p.get("rows", 0))
        for cid, st in (p.get("cols") or {}).items():
            cid = int(cid)
            dst = out["cols"].setdefault(
                cid, {"nonnull": 0, "sum": 0, "min": None, "max": None})
            dst["nonnull"] += int(st.get("nonnull", 0))
            dst["sum"] += int(st.get("sum", 0))
            for k, pick in (("min", min), ("max", max)):
                v = st.get(k)
                if v is None:
                    continue
                dst[k] = v if dst[k] is None else pick(dst[k], v)
    return out
