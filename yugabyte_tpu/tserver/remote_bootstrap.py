"""Remote bootstrap: stream a tablet snapshot to bring up a new replica.

Capability parity with the reference (ref: src/yb/tserver/
remote_bootstrap_session.h:95 — the source serves a RocksDB checkpoint
(hard-linked SSTs) + WAL segments over chunked fetch RPCs;
remote_bootstrap_client.cc — the destination downloads everything, writes a
superblock + consensus metadata and opens the tablet, after which normal
Raft catch-up replays whatever the snapshot missed).

The source does NOT pause writes: WAL segments are hard-linked while the
appender keeps writing, so the fetched tail may be torn — the destination's
WAL replay stops at the first bad record (same crash-tolerance contract as
local bootstrap) and Raft streams the rest.
"""

from __future__ import annotations

import os
import shutil
import threading
import uuid
from typing import Dict, List, Tuple

from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE

FETCH_CHUNK = 1 << 20


def _link_or_copy(src: str, dst: str) -> None:
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _snapshot_tree(src_root: str, dst_root: str) -> None:
    for dirpath, _dirnames, filenames in os.walk(src_root):
        rel = os.path.relpath(dirpath, src_root)
        out_dir = os.path.join(dst_root, rel) if rel != "." else dst_root
        os.makedirs(out_dir, exist_ok=True)
        for fn in filenames:
            if fn.endswith(".tmp"):
                continue
            _link_or_copy(os.path.join(dirpath, fn),
                          os.path.join(out_dir, fn))


class RemoteBootstrapSessions:
    """Source-side session registry (one per in-flight bootstrap)."""

    def __init__(self, fs_root: str):
        self._root = os.path.join(fs_root, "rb_sessions")
        self._lock = threading.Lock()
        self._sessions: Dict[str, str] = {}  # session_id -> dir
        shutil.rmtree(self._root, ignore_errors=True)

    def begin(self, tablet_peer, tablet_meta: dict) -> dict:
        """Flush + snapshot the tablet into a session dir; return the file
        manifest and the consensus state the destination must adopt."""
        session_id = uuid.uuid4().hex[:12]
        sdir = os.path.join(self._root, session_id)
        os.makedirs(sdir, exist_ok=True)
        tablet_peer.tablet.flush()
        # Hard-link LSM data (ref rocksdb CreateCheckpoint) + WAL segments.
        _snapshot_tree(os.path.join(tablet_peer.data_dir, "regular"),
                       os.path.join(sdir, "regular"))
        _snapshot_tree(os.path.join(tablet_peer.data_dir, "intents"),
                       os.path.join(sdir, "intents"))
        _snapshot_tree(tablet_peer.log.wal_dir, os.path.join(sdir, "wal"))
        files: List[Tuple[str, int]] = []
        for dirpath, _d, filenames in os.walk(sdir):
            for fn in filenames:
                p = os.path.join(dirpath, fn)
                files.append((os.path.relpath(p, sdir), os.path.getsize(p)))
        with self._lock:
            self._sessions[session_id] = sdir
        raft = tablet_peer.raft
        TRACE("rb session %s: %d files for tablet %s", session_id,
              len(files), tablet_peer.tablet_id)
        return {
            "session_id": session_id,
            "files": [[p, s] for p, s in files],
            "term": raft.current_term,
            "peer_ids": list(raft.config.peer_ids),
            "config_index": raft._meta.config_index,
            "tablet_meta": tablet_meta,
        }

    def _session_dir(self, session_id: str) -> str:
        with self._lock:
            sdir = self._sessions.get(session_id)
        if sdir is None:
            raise StatusError(Status.NotFound(
                f"remote bootstrap session {session_id}"))
        return sdir

    def fetch(self, session_id: str, relpath: str, offset: int,
              length: int) -> bytes:
        sdir = self._session_dir(session_id)
        p = os.path.normpath(os.path.join(sdir, relpath))
        if not p.startswith(os.path.normpath(sdir) + os.sep):
            raise StatusError(Status.InvalidArgument(
                f"path escape: {relpath!r}"))
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(min(length, FETCH_CHUNK))

    def end(self, session_id: str) -> None:
        with self._lock:
            sdir = self._sessions.pop(session_id, None)
        if sdir:
            shutil.rmtree(sdir, ignore_errors=True)


def download_tablet(messenger, source_addr: str, tablet_id: str,
                    dest_dir: str) -> dict:
    """Destination half (ref remote_bootstrap_client.cc): pull every file
    of a fresh source session into dest_dir; returns the begin-response
    (manifest + consensus state). Caller writes superblock/cmeta and opens
    the tablet."""
    resp = messenger.call(source_addr, "tserver", "begin_remote_bootstrap",
                          tablet_id=tablet_id)
    session_id = resp["session_id"]
    try:
        for relpath, size in resp["files"]:
            out = os.path.join(dest_dir, relpath)
            os.makedirs(os.path.dirname(out), exist_ok=True)
            with open(out, "wb") as f:
                off = 0
                while off < size:
                    chunk = messenger.call(
                        source_addr, "tserver", "fetch_remote_bootstrap",
                        session_id=session_id, relpath=relpath,
                        offset=off, length=FETCH_CHUNK)
                    if not chunk:
                        break
                    f.write(chunk)
                    off += len(chunk)
                f.flush()
                os.fsync(f.fileno())
    finally:
        try:
            messenger.call(source_addr, "tserver", "end_remote_bootstrap",
                           session_id=session_id)
        except StatusError:
            pass
    return resp
