"""Process-wide JAX configuration: persistent compilation cache.

TPU compiles through the axon tunnel cost seconds-to-minutes; the storage
engine's kernels use shape bucketing (ops/merge_gc.py) so a small set of
executables covers all workloads, and this persistent cache makes them a
one-time cost per MACHINE rather than per process.
"""

import os

import jax

_CACHE_DIR = os.environ.get(
    "YBTPU_JAX_CACHE_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "ybtpu_jax_cache"))

try:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # cache is an optimization; never fail import over it
    pass


def lowering_text(jitted, args, statics) -> str:
    """StableHLO text of a jitted callable lowered against abstract args
    (ShapeDtypeStructs) — no device execution, no compilation.  The
    kernel compile-surface manifest (tools/analysis/kernel_manifest.py)
    fingerprints this text per (kernel, bucket) pair; the default
    StableHLO printing carries no source positions, so pure line drift
    cannot move the fingerprint."""
    return jitted.lower(*args, **statics).as_text()
