"""Write backpressure, compaction rate limiting, whole-SST TTL drop
(round-2 Missing #6/#9; ref tserver/tablet_service.cc:1510,
rocksdb/util/rate_limiter.cc, docdb/compaction_file_filter.h:60)."""

import time

import numpy as np
import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter
from yugabyte_tpu.tablet.tablet import Tablet, TabletOptions
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.rate_limiter import RateLimiter
from yugabyte_tpu.utils.status import StatusError


def _schema():
    return Schema([ColumnSchema("k", DataType.STRING),
                   ColumnSchema("v", DataType.INT64)],
                  num_hash_key_columns=0, num_range_key_columns=1)


def _op(k, v=1, ttl_ms=None):
    return QLWriteOp(WriteOpKind.INSERT, DocKey(range_components=(k,)),
                     {"v": v}, ttl_ms=ttl_ms)


class _FlagScope:
    def __init__(self, **kv):
        self.kv = kv
        self.old = {}

    def __enter__(self):
        for k, v in self.kv.items():
            self.old[k] = flags.get_flag(k)
            flags.set_flag(k, v)

    def __exit__(self, *a):
        for k, v in self.old.items():
            flags.set_flag(k, v)


def test_write_backpressure_delays_then_rejects(tmp_path):
    t = Tablet("bp", str(tmp_path), _schema(),
               options=TabletOptions(auto_compact=False))
    with _FlagScope(sst_files_soft_limit=3, sst_files_hard_limit=6,
                    write_backpressure_max_delay_ms=120):
        # under the soft limit: no delay
        t.write([_op("a")])
        t0 = time.monotonic()
        t.write([_op("b")])
        assert time.monotonic() - t0 < 0.1
        # push files past soft: delays kick in, growing with pressure
        for i in range(4):
            t.write([_op(f"f{i}")])
            t.regular_db.flush()
        t0 = time.monotonic()
        t.write([_op("slow")])
        assert time.monotonic() - t0 >= 0.05  # scored delay
        # at the hard limit: retryable rejection (files grow with each
        # flush until the limit trips)
        rejected = None
        for i in range(6):
            try:
                t.write([_op(f"g{i}")])
            except StatusError as e:
                rejected = e
                break
            t.regular_db.flush()
        assert rejected is not None and "retry later" in str(rejected)
        assert t.metric_write_rejections.value() >= 1
        # compaction relieves the pressure and writes flow again
        t.regular_db.compact_all()
        t.write([_op("ok-again")])
    t.close()


def test_backpressure_keeps_l0_bounded_under_sustained_load(tmp_path):
    """The systemic property: with auto-compaction on and backpressure
    gating writes, a sustained write-heavy load cannot pile up unbounded
    L0 files."""
    t = Tablet("bp2", str(tmp_path), _schema(),
               options=TabletOptions(auto_compact=True))
    max_seen = 0
    with _FlagScope(sst_files_soft_limit=4, sst_files_hard_limit=10,
                    write_backpressure_max_delay_ms=30):
        for i in range(400):
            while True:
                try:
                    t.write([_op(f"k{i:05d}", i)])
                    break
                except StatusError:
                    time.sleep(0.02)  # the client retry loop
            if i % 10 == 0:
                t.regular_db.flush()
            max_seen = max(max_seen, t.regular_db.n_live_files)
        assert max_seen <= 10, f"L0 unbounded: {max_seen}"
    t.close()


def test_rate_limiter_paces_bytes():
    rl = RateLimiter(1_000_000)  # 1MB/s
    t0 = time.monotonic()
    for _ in range(4):
        rl.acquire(250_000)
    dt = time.monotonic() - t0
    # 1MB through a 1MB/s bucket with 0.5s burst: >= ~0.4s of pacing
    assert dt >= 0.3, dt
    assert rl.total_through == 1_000_000


def test_compaction_rate_limit_flag(tmp_path):
    old = flags.get_flag("compaction_rate_bytes_per_sec")
    flags.set_flag("compaction_rate_bytes_per_sec", 200_000)
    try:
        db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
        ht = 1
        for batch in range(4):
            items = []
            for i in range(300):
                key = DocKey(range_components=(f"r{i:04d}",)).encode()
                items.append((key, DocHybridTime(HybridTime(ht << 12), 0),
                              b"v" * 40))
                ht += 1
            db.write_batch(items)
            db.flush()
        old_split = flags.get_flag("compaction_max_output_entries_per_sst")
        flags.set_flag("compaction_max_output_entries_per_sst", 300)
        try:
            t0 = time.monotonic()
            db.compact_all()
            dt = time.monotonic() - t0
            assert dt >= 0.1, f"compaction unthrottled: {dt}"
        finally:
            flags.set_flag("compaction_max_output_entries_per_sst",
                           old_split)
        db.close()
    finally:
        flags.set_flag("compaction_rate_bytes_per_sec", old)


def test_whole_file_ttl_drop(tmp_path):
    """An input SST whose every entry expired before the cutoff is dropped
    without being read; files with any non-TTL entry are not."""
    from yugabyte_tpu.ops.slabs import pack_kvs
    from yugabyte_tpu.storage import compaction as C
    from yugabyte_tpu.docdb.value import Value

    def build(path, ttl_all, prefix):
        ops = []
        for i in range(50):
            v = Value(b"x", ttl_ms=1 if ttl_all else None).encode()
            key = DocKey(range_components=(f"{prefix}{i:03d}",)).encode()
            ops.append((key, ((i + 1) << 12) << 32, v))
        slab = pack_kvs(ops)
        SSTWriter(str(path)).write(slab, Frontier())
        return SSTReader(str(path))

    # DISJOINT key ranges: droppability requires that the expired file
    # cannot shadow anything in the other inputs
    expired = build(tmp_path / "exp.sst", ttl_all=True, prefix="a")
    live = build(tmp_path / "live.sst", ttl_all=False, prefix="k")
    assert expired.props.max_expire_us > 0
    assert live.props.max_expire_us == 0
    cutoff = (10_000_000_000 << 12)  # far future: everything TTL'd expired
    kept, dropped = C.filter_expired_inputs(
        [expired, live], cutoff, is_major=True, retain_deletes=False)
    assert dropped == [expired] and kept == [live]
    # not at minor compactions (expired values must survive as history)
    kept, dropped = C.filter_expired_inputs(
        [expired, live], cutoff, is_major=False, retain_deletes=False)
    assert dropped == []
    # end-to-end: the job runs with the expired file dropped and its
    # output matches the per-entry filter's (expired rows gone either way)
    ids = iter(range(1, 100))
    out = tmp_path / "out"
    out.mkdir()
    res = C.run_compaction_job([expired, live], str(out),
                               lambda: next(ids), cutoff, True,
                               device=None)
    assert res.rows_in == 100          # dropped file still counted
    assert res.rows_out == 50          # only the non-TTL file's rows
    expired.close()
    live.close()


def test_whole_file_ttl_drop_blocked_by_overlap(tmp_path):
    """Regression (round-3 review): an expired file whose key range
    overlaps another input still SHADOWS older versions there — dropping
    it would resurrect them, so it must take the per-entry path."""
    from yugabyte_tpu.ops.slabs import pack_kvs
    from yugabyte_tpu.storage import compaction as C
    from yugabyte_tpu.docdb.value import Value

    # old non-TTL version of k000 in one file...
    old = pack_kvs([(DocKey(range_components=("k000",)).encode(),
                     (1 << 12) << 32, Value(b"old").encode())])
    SSTWriter(str(tmp_path / "old.sst")).write(old, Frontier())
    # ...overwritten by an expired-TTL version in an all-TTL file
    new = pack_kvs([(DocKey(range_components=("k000",)).encode(),
                     (9 << 12) << 32, Value(b"new", ttl_ms=1).encode())])
    SSTWriter(str(tmp_path / "new.sst")).write(new, Frontier())
    r_old = SSTReader(str(tmp_path / "old.sst"))
    r_new = SSTReader(str(tmp_path / "new.sst"))
    cutoff = (10_000_000_000 << 12)
    kept, dropped = C.filter_expired_inputs(
        [r_new, r_old], cutoff, is_major=True, retain_deletes=False)
    assert dropped == []   # overlap forces the per-entry path
    ids = iter(range(1, 10))
    out = tmp_path / "out2"
    out.mkdir()
    res = C.run_compaction_job([r_new, r_old], str(out),
                               lambda: next(ids), cutoff, True,
                               device=None)
    assert res.rows_out == 0   # expired k000 shadows AND kills the old one
    r_old.close()
    r_new.close()
