"""Device mesh construction for the distributed storage fabric.

The TPU-native replacement for the reference's cluster topology: where
YugabyteDB spreads tablets across tservers connected by its RPC fabric
(SURVEY.md section 2.7), this framework spreads tablet shards across TPU
devices connected by ICI/DCN, with XLA collectives doing the data movement
(all_gather for splitter exchange, all_to_all for range repartitioning,
psum for checksums/consistency probes).

Axes:
  "shard"  - range-sharding of key space within one logical tablet group
             (the subcompaction axis; ref: compaction_job.cc:330
             GenSubcompactionBoundaries, one thread per key range -> here
             one DEVICE per key range)
A second "replica" axis arrives with the consensus layer: replica groups
mirror writes across failure domains the way per-tablet Raft groups span
tservers.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_shards: Optional[int] = None, devices: Optional[Sequence] = None,
              axis: str = "shard") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_shards is not None:
        devs = devs[:n_shards]
    return Mesh(np.array(devs), (axis,))
