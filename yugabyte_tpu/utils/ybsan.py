"""ybsan shim: the package-side face of the happens-before sanitizer.

The real detector lives in `tools/sanitizer/` (vector clocks, shadow
cells, race reports) and only exists in checkouts that carry the tools
tree. Production code must not import it — so every instrumentation
site inside yugabyte_tpu (utils/lock_rank.py acquire/release,
utils/threadpool.py submit/execute, the `@ybsan.shadow` opt-in classes)
talks to THIS module instead, and `tools.sanitizer.arm()` installs its
hook table here at arming time.

Disarmed cost (the production and plain-pytest case): every forwarder
is one module-global read plus an is-None check; `shadow(...)` returns
the class untouched and records the declaration for a later arm.

Arming is explicit: `YBSAN=1 pytest ...` (tests/conftest.py arms at
session start) or `tools.sanitizer.arm()` from a test body. The shim
never auto-imports tools — a checkout without tools/ simply can never
arm, and `enabled()` says whether the environment ASKS for arming.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# Declared shadow disciplines (see README "Concurrency sanitizer"):
# the detector checks the STATED protocol of a deliberately lock-free
# structure instead of lock possession.
SINGLE_WRITER = "single-writer"            # un-HB'd 2nd writer = race
SINGLE_WRITER_PER_KEY = "single-writer-per-key"  # per dict key
PUBLISHER_CONSUMER = "publisher-consumer"  # reads must be HB-after writes

_hooks: Optional[Any] = None

# Shadow declarations made before arming: [(cls, {attr: discipline})].
# tools/sanitizer replays these when it installs its hooks.
_shadow_registry: List[Tuple[type, Dict[str, str]]] = []


def enabled() -> bool:
    """Does the environment ask for the sanitizer? (YBSAN=1)"""
    env = os.environ.get("YBSAN")
    return env is not None and env not in ("", "0", "false", "off")


def armed() -> bool:
    return _hooks is not None


def install(hooks: Optional[Any]) -> List[Tuple[type, Dict[str, str]]]:
    """Install (or, with None, remove) the detector hook table. Called
    only by tools/sanitizer. Returns the pre-arm shadow declarations so
    the detector can patch them."""
    global _hooks
    _hooks = hooks
    return list(_shadow_registry)


# -------------------------------------------------- shared stack format
# One stack vocabulary for every sanitizer surface: ybsan race reports
# AND lock_rank's lock-order-cycle reports render through these, so the
# merged violation report reads uniformly.

def capture_stack(skip: int = 1,
                  depth: int = 10) -> Tuple[Tuple[str, int, str], ...]:
    """Cheap stack summary [(path, line, func)], innermost first,
    sanitizer frames elided."""
    out: List[Tuple[str, int, str]] = []
    f = sys._getframe(skip)
    while f is not None and len(out) < depth:
        co = f.f_code
        fn = co.co_filename
        if "sanitizer" not in fn and not fn.endswith(
                ("ybsan.py", "lock_rank.py")):
            out.append((fn, f.f_lineno, co.co_name))
        f = f.f_back
    return tuple(out)


def format_stack(stack, indent: str = "    ") -> str:
    """`at func (path:line)` per frame, innermost first."""
    lines = []
    for fn, lineno, func in stack:
        rel = os.path.relpath(fn, _REPO_ROOT) if fn.startswith(_REPO_ROOT) \
            else fn
        lines.append(f"{indent}at {func} ({rel}:{lineno})")
    return "\n".join(lines) if lines else f"{indent}<no frames>"


# ----------------------------------------------------------- forwarders
def lock_acquired(lock) -> None:
    h = _hooks
    if h is not None:
        h.lock_acquired(lock)


def lock_releasing(lock) -> None:
    h = _hooks
    if h is not None:
        h.lock_releasing(lock)


def bind_task(fn):
    """HB edge submitter -> executor: wrap a work item at submit time so
    running it joins the submitter's clock (utils/threadpool.py)."""
    h = _hooks
    if h is None:
        return fn
    return h.bind_task(fn)


def shadow(**attrs: str):
    """Class decorator declaring per-attribute lock-free disciplines:

        @ybsan.shadow(stages=ybsan.SINGLE_WRITER_PER_KEY)
        class LatencyBudget: ...

    Disarmed: returns the class unchanged (zero production cost) and
    records the declaration; arming replays the registry and patches
    the class with shadow cells that enforce the stated discipline.
    """
    spec = dict(attrs)

    def deco(cls: type) -> type:
        _shadow_registry.append((cls, spec))
        h = _hooks
        if h is not None:
            h.patch_shadow(cls, spec)
        return cls

    return deco
