"""TSTabletManager: tablet lifecycle on one tablet server.

Capability parity with the reference (ref: src/yb/tserver/ts_tablet_manager.h
:126 — creates/opens/deletes TabletPeers, persists per-tablet metadata so a
restart reopens every hosted tablet and replays its WAL; the reference keeps
RaftGroupMetadata in a superblock protobuf, here a JSON sidecar per tablet
dir). Thread-safe: RPC handlers and the heartbeater hit it concurrently.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional, Sequence

from yugabyte_tpu.utils import jsonutil

from yugabyte_tpu.common.hybrid_time import HybridClock
from yugabyte_tpu.common.wire import schema_from_wire, schema_to_wire
from yugabyte_tpu.tablet.tablet import TabletOptions
from yugabyte_tpu.tablet.tablet_peer import TabletPeer
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE


class TSTabletManager:
    def __init__(self, server_id: str, fs_root: str, transport,
                 clock: Optional[HybridClock] = None,
                 tablet_options_factory=None, metrics=None):
        self.server_id = server_id
        self.fs_root = fs_root
        self.transport = transport
        self.clock = clock or HybridClock()
        self.metrics = metrics
        self._tablet_options_factory = tablet_options_factory or TabletOptions
        self._tablets: Dict[str, TabletPeer] = {}
        self._meta: Dict[str, dict] = {}  # tablet_id -> superblock dict
        self._lock = threading.Lock()
        # Serializes whole tablet creations: two concurrent (retried /
        # reconciler-raced) create_tablet RPCs must never both open a
        # TabletPeer over the same WAL directory.
        self._create_lock = threading.Lock()
        os.makedirs(self._tablets_root, exist_ok=True)

    @property
    def _tablets_root(self) -> str:
        return os.path.join(self.fs_root, "tablets")

    def _tablet_dir(self, tablet_id: str) -> str:
        return os.path.join(self._tablets_root, tablet_id)

    # ------------------------------------------------------------- lifecycle
    def open_existing(self) -> int:
        """Reopen every tablet found on disk (restart path; ref
        TSTabletManager::Init replaying each superblock)."""
        opened = 0
        for tablet_id in sorted(os.listdir(self._tablets_root)):
            meta_path = os.path.join(self._tablet_dir(tablet_id), "meta.json")
            if not os.path.exists(meta_path):
                continue
            with open(meta_path) as f:
                meta = jsonutil.loads(f.read())
            self._open_tablet(tablet_id, meta)
            opened += 1
        return opened

    def create_tablet(self, tablet_id: str, table_id: str, schema_wire: dict,
                      peer_server_ids: Sequence[str],
                      partition_wire: Optional[dict] = None) -> None:
        """Create a brand-new tablet replica on this server (ref
        TSTabletManager::CreateNewTablet). Idempotent for retried RPCs."""
        with self._create_lock:
            with self._lock:
                if tablet_id in self._tablets:
                    return
            tdir = self._tablet_dir(tablet_id)
            meta_path = os.path.join(tdir, "meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    self._open_tablet(tablet_id, jsonutil.loads(f.read()))
                return
            meta = {"tablet_id": tablet_id, "table_id": table_id,
                    "schema": schema_wire,
                    "peer_server_ids": list(peer_server_ids),
                    "partition": partition_wire}
            os.makedirs(tdir, exist_ok=True)
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(jsonutil.dumps(meta))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, meta_path)
            self._open_tablet(tablet_id, meta)
        TRACE("ts %s: created tablet %s (table %s)",
              self.server_id, tablet_id, table_id)

    def _open_tablet(self, tablet_id: str, meta: dict) -> None:
        schema = schema_from_wire(meta["schema"])
        peer = TabletPeer(
            tablet_id, self._tablet_dir(tablet_id), schema,
            server_id=self.server_id,
            server_ids=meta["peer_server_ids"],
            transport=self.transport, clock=self.clock,
            options=self._tablet_options_factory(),
            metrics=self.metrics)
        peer.start(election_timer=True)
        with self._lock:
            self._tablets[tablet_id] = peer
            self._meta[tablet_id] = meta

    def delete_tablet(self, tablet_id: str) -> None:
        """ref TSTabletManager::DeleteTablet — shut down + remove data."""
        with self._lock:
            peer = self._tablets.pop(tablet_id, None)
            self._meta.pop(tablet_id, None)
        if peer is not None:
            self.transport.unregister(peer.raft.config.peer_id)
            peer.shutdown()
        shutil.rmtree(self._tablet_dir(tablet_id), ignore_errors=True)
        TRACE("ts %s: deleted tablet %s", self.server_id, tablet_id)

    # --------------------------------------------------------------- lookup
    def get_tablet(self, tablet_id: str) -> TabletPeer:
        with self._lock:
            peer = self._tablets.get(tablet_id)
        if peer is None:
            raise StatusError(Status.NotFound(
                f"tablet {tablet_id} not hosted on {self.server_id}"))
        return peer

    def tablet_ids(self) -> List[str]:
        with self._lock:
            return list(self._tablets)

    def tablet_meta(self, tablet_id: str) -> dict:
        with self._lock:
            return dict(self._meta.get(tablet_id) or {})

    def generate_report(self) -> List[dict]:
        """Per-tablet state for the heartbeat (ref master_heartbeat.proto
        tablet reports)."""
        with self._lock:
            peers = list(self._tablets.items())
        report = []
        for tablet_id, peer in peers:
            report.append({
                "tablet_id": tablet_id,
                "role": peer.raft.role.value,
                "term": peer.raft.current_term,
                "leader_ready": peer.raft.is_leader() and
                peer.raft.leader_ready(),
            })
        return report

    def shutdown(self) -> None:
        with self._lock:
            peers = list(self._tablets.values())
            self._tablets.clear()
        for peer in peers:
            peer.shutdown()
