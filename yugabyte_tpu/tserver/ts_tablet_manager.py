"""TSTabletManager: tablet lifecycle on one tablet server.

Capability parity with the reference (ref: src/yb/tserver/ts_tablet_manager.h
:126 — creates/opens/deletes TabletPeers, persists per-tablet metadata so a
restart reopens every hosted tablet and replays its WAL; the reference keeps
RaftGroupMetadata in a superblock protobuf, here a JSON sidecar per tablet
dir). Thread-safe: RPC handlers and the heartbeater hit it concurrently.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional, Sequence

from yugabyte_tpu.utils import jsonutil

from yugabyte_tpu.common.hybrid_time import HybridClock
from yugabyte_tpu.common.wire import schema_from_wire, schema_to_wire
from yugabyte_tpu.tablet.tablet import TabletOptions
from yugabyte_tpu.tablet.tablet_peer import TabletPeer
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE


class TSTabletManager:
    def __init__(self, server_id: str, fs_root: str, transport,
                 clock: Optional[HybridClock] = None,
                 tablet_options_factory=None, metrics=None,
                 messenger=None):
        self.server_id = server_id
        self.fs_root = fs_root
        self.transport = transport
        self.clock = clock or HybridClock()
        self.metrics = metrics
        self.messenger = messenger
        from yugabyte_tpu.tserver.remote_bootstrap import (
            RemoteBootstrapSessions)
        self.rb_sessions = RemoteBootstrapSessions(fs_root)
        self._tablet_options_factory = tablet_options_factory or TabletOptions
        self._tablets: Dict[str, TabletPeer] = {}
        self._meta: Dict[str, dict] = {}  # tablet_id -> superblock dict
        self._rb_in_progress: set = set()
        # Wired by the TabletServer after construction; tablets call it to
        # resolve foreign transaction statuses at read time.
        self.status_resolver = None
        self._lock = threading.Lock()
        # Serializes whole tablet creations: two concurrent (retried /
        # reconciler-raced) create_tablet RPCs must never both open a
        # TabletPeer over the same WAL directory. Reentrant: opening a
        # tablet can replay a SPLIT op, which creates children while the
        # lock is already held.
        self._create_lock = threading.RLock()
        os.makedirs(self._tablets_root, exist_ok=True)

    @property
    def _tablets_root(self) -> str:
        return os.path.join(self.fs_root, "tablets")

    def _tablet_dir(self, tablet_id: str) -> str:
        return os.path.join(self._tablets_root, tablet_id)

    # ------------------------------------------------------------- lifecycle
    def open_existing(self) -> int:
        """Reopen every tablet found on disk (restart path; ref
        TSTabletManager::Init replaying each superblock). A parent's SPLIT
        replay may open its children before the loop reaches their dirs, so
        re-check under the create lock; dot-dirs are crash leftovers of
        interrupted bootstraps/splits and are swept."""
        opened = 0
        for tablet_id in sorted(os.listdir(self._tablets_root)):
            if tablet_id.startswith("."):
                shutil.rmtree(os.path.join(self._tablets_root, tablet_id),
                              ignore_errors=True)
                continue
            with self._create_lock:
                with self._lock:
                    if tablet_id in self._tablets:
                        continue
                meta_path = os.path.join(self._tablet_dir(tablet_id),
                                         "meta.json")
                if not os.path.exists(meta_path):
                    continue
                self._open_tablet(tablet_id, jsonutil.read_file(meta_path))
            opened += 1
        return opened

    def create_tablet(self, tablet_id: str, table_id: str, schema_wire: dict,
                      peer_server_ids: Sequence[str],
                      partition_wire: Optional[dict] = None,
                      hash_partitioning: bool = True) -> None:
        """Create a brand-new tablet replica on this server (ref
        TSTabletManager::CreateNewTablet). Idempotent for retried RPCs."""
        with self._create_lock:
            with self._lock:
                if tablet_id in self._tablets:
                    return
            tdir = self._tablet_dir(tablet_id)
            meta_path = os.path.join(tdir, "meta.json")
            if os.path.exists(meta_path):
                self._open_tablet(tablet_id, jsonutil.read_file(meta_path))
                return
            meta = {"tablet_id": tablet_id, "table_id": table_id,
                    "schema": schema_wire,
                    "peer_server_ids": list(peer_server_ids),
                    "partition": partition_wire,
                    "hash_partitioning": hash_partitioning}
            os.makedirs(tdir, exist_ok=True)
            jsonutil.write_atomic(meta_path, meta)
            self._open_tablet(tablet_id, meta)
        TRACE("ts %s: created tablet %s (table %s)",
              self.server_id, tablet_id, table_id)

    def _open_tablet(self, tablet_id: str, meta: dict) -> None:
        import dataclasses

        from yugabyte_tpu.common.partition import (
            Partition, doc_key_bounds)
        schema = schema_from_wire(meta["schema"])
        options = self._tablet_options_factory()
        part_wire = meta.get("partition")
        if part_wire is not None:
            lower, upper = doc_key_bounds(
                Partition(part_wire["start"], part_wire["end"]),
                meta.get("hash_partitioning", True))
            options = dataclasses.replace(
                options, lower_bound_key=lower, upper_bound_key=upper)
        peer = TabletPeer(
            tablet_id, self._tablet_dir(tablet_id), schema,
            server_id=self.server_id,
            server_ids=meta["peer_server_ids"],
            transport=self.transport, clock=self.clock,
            options=options,
            metrics=self.metrics)
        # Late-bound status resolver (assigned on the manager after
        # construction): conservative pending when unset.
        peer.tablet.status_resolver = (
            lambda st, txn, read_ht=None:
            self.status_resolver(st, txn, read_ht)
            if self.status_resolver is not None
            else {"status": "pending", "commit_ht": None})
        # Closure over peer+meta: during bootstrap replay the parent is not
        # yet in self._tablets, so the hook must not look it up.
        peer.on_split = (
            lambda info, p=peer, m=meta: self._create_split_children(
                p, m, info))
        # Membership changes must survive restarts: mirror the active Raft
        # config into the superblock (ref RaftGroupMetadata config update).
        peer.raft.on_config_change = (
            lambda ids, tid=tablet_id: self._update_peers_in_meta(tid, ids))
        peer.start(election_timer=True)
        with self._lock:
            self._tablets[tablet_id] = peer
            self._meta[tablet_id] = meta
        active = sorted(p.split("/", 1)[0]
                        for p in peer.raft.config.peer_ids)
        if active != sorted(meta["peer_server_ids"]):
            self._update_peers_in_meta(
                tablet_id, tuple(peer.raft.config.peer_ids))

    def _update_peers_in_meta(self, tablet_id: str,
                              peer_ids: tuple) -> None:
        server_ids = [p.split("/", 1)[0] for p in peer_ids]
        with self._lock:
            meta = self._meta.get(tablet_id)
            if meta is None:
                return
            meta["peer_server_ids"] = server_ids
            snapshot = dict(meta)
        jsonutil.write_atomic(
            os.path.join(self._tablet_dir(tablet_id), "meta.json"), snapshot)

    # ----------------------------------------------------------- splitting
    def _create_split_children(self, parent, parent_meta: dict,
                               info: dict) -> None:
        """SPLIT-op apply hook: snapshot the parent into two child tablets
        (hard links) with halved partitions. Idempotent — re-invoked on WAL
        replay after restart (ref tablet.cc:3338 CreateSubtablet)."""
        from yugabyte_tpu.tserver.remote_bootstrap import _snapshot_tree
        parent_id = parent.tablet_id
        split_pk = bytes.fromhex(info["split_partition_key"])
        part = parent_meta.get("partition") or {"start": b"", "end": b""}
        child_parts = [{"start": part["start"], "end": split_pk},
                       {"start": split_pk, "end": part["end"]}]
        parent.tablet.flush()
        for child_id, child_part in zip(info["children"], child_parts):
            with self._create_lock:
                with self._lock:
                    already = child_id in self._tablets
                if already:
                    self._inherit_retryable(parent, child_id)
                    continue
                cdir = self._tablet_dir(child_id)
                if os.path.exists(os.path.join(cdir, "meta.json")):
                    self._open_tablet(child_id, jsonutil.read_file(
                        os.path.join(cdir, "meta.json")))
                    self._inherit_retryable(parent, child_id)
                    continue
                tmp_dir = os.path.join(self._tablets_root,
                                       f".split-{child_id}")
                shutil.rmtree(tmp_dir, ignore_errors=True)
                _snapshot_tree(os.path.join(parent.data_dir, "regular"),
                               os.path.join(tmp_dir, "regular"))
                _snapshot_tree(os.path.join(parent.data_dir, "intents"),
                               os.path.join(tmp_dir, "intents"))
                meta = {"tablet_id": child_id,
                        "table_id": parent_meta["table_id"],
                        "schema": parent_meta["schema"],
                        "peer_server_ids": [
                            p.split("/", 1)[0]
                            for p in parent.raft.config.peer_ids],
                        "partition": child_part,
                        "hash_partitioning": parent_meta.get(
                            "hash_partitioning", True),
                        "split_parent": parent_id}
                jsonutil.write_atomic(os.path.join(tmp_dir, "meta.json"),
                                      meta)
                shutil.rmtree(cdir, ignore_errors=True)
                os.rename(tmp_dir, cdir)
                self._open_tablet(child_id, meta)
            # exactly-once dedup survives the split on EVERY path (fresh
            # create, replay re-open, already-open): children adopt the
            # parent's retryable-request records — the data they inherited
            # includes those writes
            self._inherit_retryable(parent, child_id)
        TRACE("ts %s: split %s -> %s", self.server_id, parent_id,
              info["children"])

    def _inherit_retryable(self, parent, child_id: str) -> None:
        with self._lock:
            child = self._tablets.get(child_id)
        if child is not None:
            child.tablet.retryable.inherit_from(parent.tablet.retryable)

    def split_tablet(self, tablet_id: str) -> List[str]:
        """Leader-side split entry: compute the split point and replicate
        the SPLIT op (ref master's TabletSplitManager driving
        tserver SplitTablet RPCs)."""
        peer = self.get_tablet(tablet_id)
        meta = self.tablet_meta(tablet_id)
        if peer.tablet.split_children is not None:
            return list(peer.tablet.split_children)
        split_pk = peer.tablet.split_partition_key(
            meta.get("hash_partitioning", True))
        if split_pk is None:
            raise StatusError(Status.IllegalState(
                f"tablet {tablet_id} has too little data to split"))
        part = meta.get("partition") or {"start": b"", "end": b""}
        if not (part["start"] < split_pk
                and (not part["end"] or split_pk < part["end"])):
            raise StatusError(Status.IllegalState(
                f"median key outside partition; cannot split {tablet_id}"))
        children = [f"{tablet_id}.s0", f"{tablet_id}.s1"]
        peer.submit_split(children, split_pk)
        return children

    # ------------------------------------------------------ remote bootstrap
    def start_remote_bootstrap(self, tablet_id: str,
                               source_addr: str) -> None:
        """Destination path: download a snapshot from source_addr and open
        the replica (ref remote_bootstrap_client.cc). Idempotent: a replica
        that already exists locally is left alone."""
        from yugabyte_tpu.tablet.tablet_peer import STATE_FAILED
        from yugabyte_tpu.tserver.remote_bootstrap import download_tablet
        with self._create_lock:
            with self._lock:
                cur = self._tablets.get(tablet_id)
            if cur is not None:
                if not (cur.state == STATE_FAILED
                        and getattr(cur, "failed_corrupt", False)):
                    return
                # Corruption-failed replica: its on-disk data is bad and
                # sticky (retry refuses to clear it) — tear it down and
                # rebuild in place from the healthy source the master
                # pointed us at. Never done to a healthy replica: the
                # idempotent-skip above protects those.
                TRACE("ts %s: rebuilding corrupt replica %s from %s",
                      self.server_id, tablet_id, source_addr)
                self.delete_tablet(tablet_id)
            tdir = self._tablet_dir(tablet_id)
            if os.path.exists(os.path.join(tdir, "meta.json")):
                self._open_tablet(
                    tablet_id,
                    jsonutil.read_file(os.path.join(tdir, "meta.json")))
                return
            with self._lock:
                if tablet_id in self._rb_in_progress:
                    return  # another thread is already downloading it
                self._rb_in_progress.add(tablet_id)
        # Download OUTSIDE the create lock: a multi-GB transfer must not
        # head-of-line-block every other tablet creation on this server.
        tmp_dir = os.path.join(self._tablets_root, f".rb-{tablet_id}")
        try:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            os.makedirs(tmp_dir, exist_ok=True)
            resp = download_tablet(self.messenger, source_addr, tablet_id,
                                   tmp_dir)
        except BaseException:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            with self._lock:
                self._rb_in_progress.discard(tablet_id)
            raise
        with self._create_lock:
            with self._lock:
                self._rb_in_progress.discard(tablet_id)
                if tablet_id in self._tablets:
                    shutil.rmtree(tmp_dir, ignore_errors=True)
                    return
            src_meta = resp["tablet_meta"]
            meta = {"tablet_id": tablet_id,
                    "table_id": src_meta["table_id"],
                    "schema": src_meta["schema"],
                    "peer_server_ids": [p.split("/", 1)[0]
                                        for p in resp["peer_ids"]],
                    "partition": src_meta.get("partition"),
                    "hash_partitioning": src_meta.get("hash_partitioning",
                                                      True),
                    "split_parent": src_meta.get("split_parent")}
            # Fresh vote record at the source's term; adopting the source's
            # votes could double-vote in an in-flight election.
            jsonutil.write_atomic(
                os.path.join(tmp_dir, "cmeta.json"),
                {"term": resp["term"], "voted_for": None,
                 "peer_ids": resp["peer_ids"],
                 "config_index": resp["config_index"]})
            jsonutil.write_atomic(os.path.join(tmp_dir, "meta.json"), meta)
            shutil.rmtree(tdir, ignore_errors=True)
            os.rename(tmp_dir, tdir)
            self._open_tablet(tablet_id, meta)
        TRACE("ts %s: remote-bootstrapped tablet %s from %s",
              self.server_id, tablet_id, source_addr)

    def recover_failed_tablet(self, tablet_id: str) -> bool:
        """Bring a FAILED replica back: in-place first (clears DB
        background errors and retries the parked flush), then — for
        failures a live process cannot undo, like a sealed WAL with a torn
        tail — a full re-bootstrap: shut the peer down and reopen it from
        its on-disk state so the normal torn-tail replay + leader catch-up
        rules apply (ref: the reference tombstones and re-bootstraps
        failed replicas). Returns True when the replica is RUNNING."""
        from yugabyte_tpu.tablet.tablet_peer import STATE_FAILED
        peer = self.get_tablet(tablet_id)
        if peer.state != STATE_FAILED:
            return True
        if peer.try_recover():
            return True
        if peer.log.io_error is None:
            # a DB background error that STILL fails to clear means the
            # disk is still bad — re-bootstrapping onto the same disk
            # cannot help; stay parked and let the backoff retry again
            return False
        with self._create_lock:
            with self._lock:
                cur = self._tablets.get(tablet_id)
                if cur is not peer:
                    # replaced concurrently (another recovery / delete)
                    return cur is not None and cur.state != STATE_FAILED
                self._tablets.pop(tablet_id)
                meta = self._meta.pop(tablet_id)
            self.transport.unregister(peer.raft.config.peer_id)
            try:
                peer.shutdown()
            except OSError as e:
                TRACE("ts %s: shutdown of failed tablet %s raised: %s",
                      self.server_id, tablet_id, e)
            self._open_tablet(tablet_id, meta)
        TRACE("ts %s: re-bootstrapped failed tablet %s", self.server_id,
              tablet_id)
        return True

    def delete_tablet(self, tablet_id: str) -> None:
        """ref TSTabletManager::DeleteTablet — shut down + remove data."""
        with self._lock:
            peer = self._tablets.pop(tablet_id, None)
            self._meta.pop(tablet_id, None)
        if peer is not None:
            self.transport.unregister(peer.raft.config.peer_id)
            peer.shutdown()
        shutil.rmtree(self._tablet_dir(tablet_id), ignore_errors=True)
        TRACE("ts %s: deleted tablet %s", self.server_id, tablet_id)

    # --------------------------------------------------------------- lookup
    def get_tablet(self, tablet_id: str) -> TabletPeer:
        with self._lock:
            peer = self._tablets.get(tablet_id)
        if peer is None:
            raise StatusError(Status.NotFound(
                f"tablet {tablet_id} not hosted on {self.server_id}"))
        return peer

    def peers(self) -> List[TabletPeer]:
        """Atomic snapshot of all hosted peers (memory arbiter, reports)."""
        with self._lock:
            return list(self._tablets.values())

    def alter_tablet_schema(self, tablet_id: str, schema_wire: dict,
                            version: int) -> bool:
        """Apply an online schema change to a hosted tablet (ref
        TSTabletManager + tablet AlterSchema; versions are monotonic and
        retries idempotent).  Returns True when applied or already at
        `version`."""
        with self._lock:
            peer = self._tablets.get(tablet_id)
        if peer is None:
            raise StatusError(Status.NotFound(
                f"tablet {tablet_id} not hosted on {self.server_id}"))
        with self._create_lock:
            # re-read under the serializing lock: a concurrent NEWER alter
            # (direct push racing a heartbeat piggyback) must not be
            # overwritten by this older one
            with self._lock:
                meta = self._meta.get(tablet_id)
            if meta is None:
                raise StatusError(Status.NotFound(
                    f"tablet {tablet_id} not hosted on {self.server_id}"))
            if meta.get("schema_version", 0) >= version:
                return True
            meta = dict(meta, schema=schema_wire, schema_version=version)
            jsonutil.write_atomic(
                os.path.join(self._tablet_dir(tablet_id), "meta.json"),
                meta)
            with self._lock:
                self._meta[tablet_id] = meta
            if peer.tablet is not None:
                peer.tablet.schema = schema_from_wire(schema_wire)
        TRACE("ts %s: tablet %s schema -> v%d", self.server_id, tablet_id,
              version)
        return True

    def apply_history_retention(self, overrides) -> None:
        """Heartbeat piggyback: per-tablet minimum MVCC history retention
        required by the master's active snapshot schedules (PITR).

        None (older master / probe path) is a no-op; a dict is the complete
        view — hosted tablets absent from it reset to zero so a deleted
        schedule releases its deep retention."""
        if overrides is None:
            return
        for peer in self.peers():
            if peer.tablet is not None:
                peer.tablet.retention_policy.set_override(
                    overrides.get(peer.tablet_id, 0.0))

    def tablet_ids(self) -> List[str]:
        with self._lock:
            return list(self._tablets)

    def tablet_meta(self, tablet_id: str) -> dict:
        with self._lock:
            return dict(self._meta.get(tablet_id) or {})

    def generate_report(self) -> List[dict]:  # yblint: wire-pair(tablet_report, writes)
        """Per-tablet state for the heartbeat (ref master_heartbeat.proto
        tablet reports)."""
        with self._lock:
            peers = list(self._tablets.items())
        report = []
        for tablet_id, peer in peers:
            role, _commit = peer.raft.observed_state()
            entry = {
                "tablet_id": tablet_id,
                "role": role.value,
                # FAILED replicas are reported so the master's load
                # balancer can re-replicate without waiting for the whole
                # server to go silent (ref tablet reports carrying
                # RaftGroupStatePB / tablet data state).
                "state": peer.state,
                # corruption-failed replicas (scrub / read-path CRC /
                # digest divergence) are rebuilt IN PLACE from a healthy
                # peer — the disk is fine, the data is not, so no spare
                # server is needed
                "failed_corrupt": bool(getattr(peer, "failed_corrupt",
                                               False)),
                "term": peer.raft.current_term,
                "leader_ready": peer.raft.is_leader() and
                peer.raft.leader_ready(),
                "replica_servers": [p.split("/", 1)[0]
                                    for p in peer.raft.config.peer_ids],
                # For stale-replica detection: a replica whose config is
                # older than the authoritative one AND that is no longer a
                # voter gets torn down by the master. COMMITTED configs
                # only — an uncommitted removal may yet be overwritten.
                "config_index": peer.raft.committed_config_index(),
            }
            meta = self.tablet_meta(tablet_id)
            entry["schema_version"] = meta.get("schema_version", 0)
            if meta.get("split_parent"):
                # Enough context for the master to ADOPT a split child it
                # has never heard of (ref tablet reports carrying
                # split_parent_tablet_id in master_heartbeat.proto).
                entry["split_parent"] = meta["split_parent"]
                entry["table_id"] = meta["table_id"]
                entry["partition"] = meta.get("partition")
            # (split children are NOT piggybacked on the parent's entry:
            # the master adopts each child from the child's own report —
            # see `split_parent` above — and derives parent completeness
            # from its catalog, so a parent-side list was dead wire
            # weight the wire-drift lint now rejects.)
            report.append(entry)
        return report

    def shutdown(self) -> None:
        with self._lock:
            peers = list(self._tablets.values())
            self._tablets.clear()
        for peer in peers:
            peer.shutdown()
