"""kernel-contracts: recompile hazards, compile-surface drift, and
prewarm/policy coverage for every jitted kernel family.

The compile surface is the product's scarcest budget (cold compile is
~107s per bucket on the tunnel TPU); ROADMAP item 5 demands every kernel
land inside the bucket/prewarm/cache discipline. This pass makes that a
check, in three coupled pieces:

1. RECOMPILE HAZARDS — static, whole-program (the ProjectIndex jit
   registry: decorated roots, `w = jax.jit(f, ...)` wrappers, and
   lru_cache-decorated jit FACTORIES whose parameters are compile keys,
   e.g. parallel/dist_compact.dist_compact_fn):
   - `weak-scalar-operand`: a Python numeric literal passed in a TRACED
     position — weak-typed scalars re-specialize the executable per
     dtype promotion; wrap in jnp.<dtype>(...) or np.asarray.
   - `unhashable-static`: a list/dict/set literal passed to a static
     parameter of a CROSS-module jit callable (same-module sites are
     jit-trace-safety's); statics must be hashable.
   - `jit-in-loop` / `jit-per-call`: `jax.jit(...)` (or
     `partial(jax.jit, ...)`) constructed inside a loop or per-call
     function body mints a fresh trace cache every evaluation; hoist to
     module level or memoize the builder with functools.lru_cache (the
     dist_compact_fn idiom — lru_cache-decorated builders are exempt).
   - `captured-host-array`: a module-level numpy array read inside a jit
     root constant-folds into the HLO (the multi-MB-literal compile blowup
     merge_network's `pos` operand exists to prevent); pass it as an
     operand instead.
   - `unquantized-static`: a shape-flavored static argument (k_pad, m,
     w, n_cmp, ...) whose value does not route through the quantization
     lattice — quantize_width/_quantize_cmp/run_bucket/bucket_size/
     default_tile, a `.bit_length()` derivation or a `1 << ...` mint —
     so every distinct runtime value would compile a fresh executable.
     Resolution is conservative: a binding the pass cannot see (a
     parameter, loop target, or opaque unpacking) is accepted; only a
     visible non-lattice derivation (e.g. `x.shape[1] // k`) is flagged.

2. MANIFEST DRIFT + BUDGET — the committed compile-surface manifest
   (tools/analysis/kernel_manifest.json) must match the current kernel
   sources (per-family AST fingerprints) and stay within each family's
   distinct-executable budget. Drift fails tier-1 until the manifest is
   regenerated (`python -m tools.analysis.kernel_manifest --write`) and
   the surface diff reviewed.

3. PREWARM + POLICY COVERAGE — every manifest bucket must either be
   covered by prewarm_buckets/PrewarmKernelsOp (`prewarmed: true`) or be
   a justified baseline entry (`unwarmed-bucket` findings carry a stable
   per-bucket fingerprint, so each deliberately-cold bucket is one
   justified line in tools/analysis/baseline.txt, not a code comment);
   prewarm shapes that match no reachable bucket are `overwarmed-bucket`
   findings; and each bucket's offload-policy quarantine key must be the
   (k_pad, m) projection storage/offload_policy.bucket_key speaks
   (`policy-key-mismatch`).

Waive a deliberate hazard with `# yblint: disable=kernel-contracts`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import AnalysisPass, FileContext, Finding
from tools.analysis.project_index import ProjectIndex, dotted_name

PASS_NAME = "kernel-contracts"

_MANIFEST_ANCHOR = "yugabyte_tpu/ops/run_merge.py"

# static parameter names that carry shapes into the compile key — the
# lattice check applies only to these (booleans and impl selectors are
# 2-valued and bounded by construction)
_SHAPE_STATICS = {"k_pad", "m", "m_c", "w", "w_route", "n_cmp", "n_sort",
                  "n_out_pad", "n_iters", "tile", "capacity", "n_pad",
                  "n", "width"}

# the quantizer vocabulary: a call to one of these produces a lattice
# point by construction
_QUANTIZERS = {"quantize_width", "_quantize_cmp", "run_bucket",
               "bucket_size", "default_tile", "packed_run_ns"}

# pass-through callables: quantized iff every argument is
_TRANSPARENT_CALLS = {"min", "max", "int", "abs", "tuple", "round", "len"}
# len() of a runtime container is NOT a lattice point
_TRANSPARENT_CALLS.discard("len")

# attribute reads accepted as lattice points (set by staging code that
# quantized them at construction)
_LATTICE_ATTRS = {"k_pad", "m", "w", "n_cmp", "n_pad", "n_sort",
                  "cmp_rows", "n_out_pad", "m_c", "tile"}
# attribute reads that are raw runtime shapes — the classic per-size
# recompile hazard when they reach a static position
_RAW_SHAPE_ATTRS = {"shape", "size", "ndim", "nbytes"}

_NP_MODULES = {"np", "numpy", "onp"}
_NP_ARRAY_CTORS = {"array", "arange", "zeros", "ones", "full", "empty",
                   "asarray", "concatenate", "tile", "eye", "linspace"}

_CACHE_DECORATORS = {"lru_cache", "cache"}


def _is_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _jit_partial(node: ast.AST) -> Optional[ast.Call]:
    if (isinstance(node, ast.Call)
            and dotted_name(node.func) in ("functools.partial", "partial")
            and node.args and _is_jit(node.args[0])):
        return node
    return None


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _static_names(call: ast.Call, params: Sequence[str],
                  mi) -> Set[str]:
    """static_argnames/static_argnums constants -> parameter names,
    resolving a bare Name spec through the module constants (the
    `_FUSED_STATICS` idiom)."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Name):
                v = mi.constants.get(kw.value.id)
                if isinstance(v, tuple):
                    out |= {s for s in v if isinstance(s, str)}
                    continue
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) \
                        and isinstance(c.value, int) \
                        and 0 <= c.value < len(params):
                    out.add(params[c.value])
    return out


class _JitRoot:
    """One jitted callable (or lru_cache jit factory): its params and
    which of them are compile keys."""

    __slots__ = ("fq", "params", "statics", "is_factory", "node",
                 "relpath")

    def __init__(self, fq: str, params: Sequence[str], statics: Set[str],
                 is_factory: bool, node: Optional[ast.AST],
                 relpath: str):
        self.fq = fq
        self.params = list(params)
        self.statics = statics
        self.is_factory = is_factory
        self.node = node
        self.relpath = relpath


def _has_cache_decorator(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        if dotted_name(d).rpartition(".")[2] in _CACHE_DECORATORS:
            return True
    return False


def _build_registry(index: ProjectIndex) -> Dict[str, _JitRoot]:
    reg: Dict[str, _JitRoot] = {}
    for mi in index.modules.values():
        ctx = mi.ctx
        for node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            params = _param_names(node)
            for dec in node.decorator_list:
                call = dec if isinstance(dec, ast.Call) \
                    and _is_jit(dec.func) else _jit_partial(dec)
                statics: Optional[Set[str]] = None
                if _is_jit(dec):
                    statics = set()
                elif call is not None:
                    statics = _static_names(call, params, mi)
                if statics is not None:
                    fq = mi.modname + "." + ctx.qualname(node)
                    reg[fq] = _JitRoot(fq, params, statics, False, node,
                                       mi.relpath)
                    break
            else:
                # lru_cache-decorated factory that builds a jit inside:
                # its parameters ARE the compile key
                if _has_cache_decorator(node) and any(
                        isinstance(c, ast.Call)
                        and (_is_jit(c.func)
                             or _jit_partial(c) is not None)
                        for c in ast.walk(node)):
                    fq = mi.modname + "." + ctx.qualname(node)
                    reg[fq] = _JitRoot(fq, params, set(params), True,
                                       node, mi.relpath)
        for asn in ctx.nodes_of(ast.Assign):
            v = asn.value
            call = None
            target_fn = None
            if isinstance(v, ast.Call) and _is_jit(v.func) and v.args \
                    and isinstance(v.args[0], ast.Name):
                call, target_fn = v, v.args[0].id
            elif isinstance(v, ast.Call) \
                    and _jit_partial(v.func) is not None and v.args \
                    and isinstance(v.args[0], ast.Name):
                call, target_fn = _jit_partial(v.func), v.args[0].id
            if call is None:
                continue
            fi = index.lookup_function(index.resolve(mi, target_fn))
            params = _param_names(fi.node) if fi is not None else []
            statics = _static_names(call, params, mi)
            for t in asn.targets:
                if isinstance(t, ast.Name):
                    fq = mi.modname + "." + t.id
                    reg[fq] = _JitRoot(fq, params, statics, False,
                                       fi.node if fi else None,
                                       mi.relpath)
    return reg


# ---------------------------------------------------------------------------
# Lattice-discipline expression check
# ---------------------------------------------------------------------------

class _LatticeChecker:
    """Is this expression a quantized lattice point?  Conservative:
    unresolvable bindings are accepted (missed hazards, never invented
    ones); visibly shape-derived values are rejected."""

    def __init__(self, index: ProjectIndex, mi, env: Dict[str, object]):
        self.index = index
        self.mi = mi
        self.env = env          # local name -> assigned expr | None
        self._visiting: Set[str] = set()

    def ok(self, expr: ast.AST, depth: int = 0) -> bool:
        if depth > 12 or expr is None:
            return True
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.UnaryOp):
            return self.ok(expr.operand, depth + 1)
        if isinstance(expr, (ast.Compare, ast.BoolOp)):
            return True         # boolean-valued: 2-point lattice
        if isinstance(expr, ast.IfExp):
            return self.ok(expr.body, depth + 1) \
                and self.ok(expr.orelse, depth + 1)
        if isinstance(expr, ast.Name):
            if expr.id in self._visiting:
                return True
            if expr.id not in self.env:
                # module-level int constant, parameter, loop target, or
                # otherwise out of sight: accept
                return True
            bound = self.env[expr.id]
            if bound is None:
                return True     # opaque binding (unpacking, for-target)
            self._visiting.add(expr.id)
            try:
                return self.ok(bound, depth + 1)
            finally:
                self._visiting.discard(expr.id)
        if isinstance(expr, ast.Attribute):
            if expr.attr in _RAW_SHAPE_ATTRS:
                return False
            return True         # lattice attrs and unknown carriers
        if isinstance(expr, ast.Subscript):
            return self.ok(expr.value, depth + 1)
        if isinstance(expr, ast.BinOp):
            if isinstance(expr.op, ast.LShift):
                return True     # `1 << ...` mints a power of two
            return self.ok(expr.left, depth + 1) \
                and self.ok(expr.right, depth + 1)
        if isinstance(expr, ast.GeneratorExp):
            return all(self.ok(g.iter, depth + 1)
                       for g in expr.generators)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return all(self.ok(e, depth + 1) for e in expr.elts)
        if isinstance(expr, ast.Call):
            leaf = dotted_name(expr.func).rpartition(".")[2]
            if leaf == "bit_length":
                return True
            if leaf in _QUANTIZERS:
                return True
            fq = self.index.resolve(self.mi, dotted_name(expr.func))
            if fq and fq.rpartition(".")[2] in _QUANTIZERS:
                return True
            if leaf in _TRANSPARENT_CALLS:
                return all(self.ok(a, depth + 1) for a in expr.args)
            return True         # unknown callable: accept (no-FP bias)
        return True


def _local_env(fn: ast.AST) -> Dict[str, object]:
    """name -> assigned expr for simple assignments; None for opaque
    bindings (tuple-unpack of a non-tuple, loop targets, with-as)."""
    env: Dict[str, object] = {}

    def opaque(target: ast.AST) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                env.setdefault(n.id, None)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                env[t.id] = node.value
            elif isinstance(t, ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(t.elts) == len(node.value.elts):
                for te, ve in zip(t.elts, node.value.elts):
                    if isinstance(te, ast.Name):
                        env[te.id] = ve
                    else:
                        opaque(te)
            else:
                opaque(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            opaque(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            opaque(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    opaque(item.optional_vars)
    return env


# ---------------------------------------------------------------------------
# Manifest drift + coverage (pure over the committed JSON; fixture tests
# inject synthetic manifests/prewarm shapes directly)
# ---------------------------------------------------------------------------

def coverage_problems(manifest: Optional[dict],
                      prewarm_shapes: Optional[Sequence] = None
                      ) -> List[Tuple[str, str, str]]:
    """(code, fingerprint-token, message) coverage findings over a
    manifest dict: unwarmed-but-reachable buckets, warmed-but-unreachable
    prewarm shapes, and quarantine keys the offload policy would not
    compute for the bucket."""
    out: List[Tuple[str, str, str]] = []
    if not manifest:
        return out
    fams = manifest.get("families", {})
    for name in sorted(fams):
        for e in fams[name].get("entries", ()):
            token = f"{name} {e.get('key')}"
            if not e.get("prewarmed"):
                out.append((
                    "unwarmed-bucket", token,
                    f"reachable bucket {e.get('key')!r} of kernel family "
                    f"{name!r} is not covered by prewarm_buckets/"
                    "PrewarmKernelsOp — its first real launch pays the "
                    "full cold compile; warm it, or justify the cold "
                    "start in tools/analysis/baseline.txt"))
            qk = e.get("quarantine_key")
            b = e.get("bucket", {})
            if qk is not None and "k_pad" in b and "m" in b \
                    and list(qk) != [b["k_pad"], b["m"]]:
                out.append((
                    "policy-key-mismatch", token,
                    f"bucket {e.get('key')!r} of {name!r} declares "
                    f"quarantine key {qk} but offload_policy.bucket_key "
                    f"would compute ({b['k_pad']}, {b['m']}) — the "
                    "device-fault quarantine would never match this "
                    "bucket"))
    if prewarm_shapes:
        rm = fams.get("run_merge_fused", {})
        reachable = {(e["bucket"].get("k_pad"), e["bucket"].get("m"),
                      e["bucket"].get("w"), e["bucket"].get("n_cmp"))
                     for e in rm.get("entries", ())}
        for shape in prewarm_shapes:
            t = tuple(int(x) for x in shape)
            if len(t) == 4 and t not in reachable:
                out.append((
                    "overwarmed-bucket",
                    "run_merge_fused prewarm "
                    f"k_pad={t[0]} m={t[1]} w={t[2]} n_cmp={t[3]}",
                    f"prewarm shape {t} matches no reachable manifest "
                    "bucket — it warms an executable nothing launches "
                    "(stale prewarm list or stale manifest)"))
    return out


class KernelContractsPass(AnalysisPass):
    name = PASS_NAME
    needs_index = True

    def __init__(self, manifest_path: Optional[str] = None):
        from tools.analysis.kernel_manifest import MANIFEST_PATH
        self.manifest_path = manifest_path or MANIFEST_PATH

    def applies_to(self, relpath: str) -> bool:
        return relpath.endswith(".py")

    # ------------------------------------------------------------------ run
    def run(self, ctx: FileContext, index: Optional[ProjectIndex] = None
            ) -> List[Finding]:
        if index is None:
            index = ProjectIndex([ctx])
        mi = index.by_relpath.get(ctx.relpath)
        if mi is None:
            return []
        reg: Dict[str, _JitRoot] = index.memo(
            "kernel_contracts.registry", lambda: _build_registry(index))
        findings: List[Finding] = []
        self._check_construction_sites(ctx, findings)
        if reg:
            self._check_call_sites(ctx, index, mi, reg, findings)
            self._check_captured_arrays(ctx, mi, reg, findings)
        if ctx.relpath == _MANIFEST_ANCHOR:
            findings.extend(self._manifest_findings(ctx, mi))
        return findings

    # ------------------------------------------- jit construction placement
    def _check_construction_sites(self, ctx: FileContext,
                                  findings: List[Finding]) -> None:
        decorator_nodes: Set[int] = set()
        for fn in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            for dec in fn.decorator_list:
                for n in ast.walk(dec):
                    decorator_nodes.add(id(n))
        for call in ctx.nodes_of(ast.Call):
            is_ctor = _is_jit(call.func) or _jit_partial(call) is not None
            if not is_ctor or id(call) in decorator_nodes:
                continue
            # the inner `partial(jax.jit, ...)` of a partial(...)(f) chain
            # is covered by its enclosing call; skip the nested node
            parent = ctx.parent(call)
            if isinstance(parent, ast.Call) and parent.func is call:
                continue
            in_loop = any(isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                          for a in ctx.ancestors(call))
            fn = ctx.enclosing_function(call)
            if in_loop:
                findings.append(ctx.finding(
                    self.name, "jit-in-loop", call,
                    "jax.jit constructed inside a loop mints a fresh "
                    "trace cache per iteration — hoist it to module "
                    "level (or an lru_cache builder)"))
            elif fn is not None and not _has_cache_decorator(fn):
                findings.append(ctx.finding(
                    self.name, "jit-per-call", call,
                    "jax.jit constructed inside a function body compiles "
                    "on every call — hoist to module level or memoize "
                    "the builder with functools.lru_cache (the "
                    "dist_compact_fn idiom)"))

    # ------------------------------------------------------------ call sites
    def _local_aliases(self, index, mi, fn_node: ast.AST,
                       reg: Dict[str, _JitRoot]) -> Dict[str, _JitRoot]:
        out: Dict[str, _JitRoot] = {}
        for node in ast.walk(fn_node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            cands = [v.body, v.orelse] if isinstance(v, ast.IfExp) else [v]
            for c in cands:
                fq = index.resolve(mi, dotted_name(c))
                if fq in reg:
                    out[node.targets[0].id] = reg[fq]
                    break
        return out

    def _resolve_root(self, index, mi, func: ast.AST,
                      aliases: Dict[str, _JitRoot],
                      reg: Dict[str, _JitRoot]
                      ) -> Tuple[Optional[_JitRoot], int]:
        """(root, positional offset).  `fn.lower(...)` / `fn.eval_shape`
        forward their arguments to the jitted signature unchanged."""
        if isinstance(func, ast.Attribute) \
                and func.attr in ("lower", "eval_shape"):
            root, _ = self._resolve_root(index, mi, func.value, aliases,
                                         reg)
            return root, 0
        if isinstance(func, ast.Name) and func.id in aliases:
            return aliases[func.id], 0
        fq = index.resolve(mi, dotted_name(func))
        return (reg.get(fq), 0) if fq else (None, 0)

    def _check_call_sites(self, ctx: FileContext, index, mi,
                          reg: Dict[str, _JitRoot],
                          findings: List[Finding]) -> None:
        for fn_node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            aliases = self._local_aliases(index, mi, fn_node, reg)
            env = None
            for call in ast.walk(fn_node):
                if not isinstance(call, ast.Call):
                    continue
                root, _off = self._resolve_root(index, mi, call.func,
                                                aliases, reg)
                if root is None:
                    continue
                if env is None:
                    env = _local_env(fn_node)
                checker = _LatticeChecker(index, mi, env)
                self._check_one_call(ctx, mi, call, root, checker,
                                     findings)

    def _check_one_call(self, ctx: FileContext, mi, call: ast.Call,
                        root: _JitRoot, checker: _LatticeChecker,
                        findings: List[Finding]) -> None:
        pairs: List[Tuple[Optional[str], ast.AST]] = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                break
            pairs.append((root.params[i] if i < len(root.params) else None,
                          a))
        for kw in call.keywords:
            if kw.arg:
                pairs.append((kw.arg, kw.value))
        cross_module = root.relpath != ctx.relpath
        for pname, value in pairs:
            is_static = pname is not None and pname in root.statics
            if is_static:
                if cross_module and isinstance(
                        value, (ast.List, ast.Dict, ast.Set)):
                    findings.append(ctx.finding(
                        self.name, "unhashable-static", value,
                        f"static arg {pname!r} of "
                        f"{root.fq.rpartition('.')[2]} passed a "
                        f"{type(value).__name__.lower()} literal — "
                        "statics must be hashable (use a tuple)"))
                    continue
                if pname in _SHAPE_STATICS and not checker.ok(value):
                    findings.append(ctx.finding(
                        self.name, "unquantized-static", value,
                        f"shape static {pname!r} of "
                        f"{root.fq.rpartition('.')[2]} bypasses the "
                        "quantization lattice (quantize_width/"
                        "_quantize_cmp/run_bucket/bucket_size/"
                        "bit_length) — every distinct runtime value "
                        "compiles a fresh executable"))
            elif not root.is_factory:
                if isinstance(value, ast.Constant) \
                        and isinstance(value.value, (int, float)) \
                        and not isinstance(value.value, bool):
                    findings.append(ctx.finding(
                        self.name, "weak-scalar-operand", value,
                        f"Python scalar literal passed in traced "
                        f"position {pname or '<pos>'} of "
                        f"{root.fq.rpartition('.')[2]} — weak-typed "
                        "scalars re-specialize the executable under "
                        "dtype promotion; wrap in jnp.<dtype>(...)"))

    # ----------------------------------------------------- captured arrays
    def _check_captured_arrays(self, ctx: FileContext, mi,
                               reg: Dict[str, _JitRoot],
                               findings: List[Finding]) -> None:
        np_arrays: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                d = dotted_name(stmt.value.func)
                mod, _, leaf = d.rpartition(".")
                if mod in _NP_MODULES and leaf in _NP_ARRAY_CTORS:
                    np_arrays.add(stmt.targets[0].id)
        if not np_arrays:
            return
        root_nodes = [r.node for r in reg.values()
                      if r.relpath == ctx.relpath and r.node is not None]
        for fn in root_nodes:
            stores = {n.id for n in ast.walk(fn)
                      if isinstance(n, ast.Name)
                      and isinstance(n.ctx, (ast.Store, ast.Del))}
            params = set(_param_names(fn))
            for n in ast.walk(fn):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in np_arrays \
                        and n.id not in stores and n.id not in params:
                    findings.append(ctx.finding(
                        self.name, "captured-host-array", n,
                        f"module-level numpy array {n.id!r} captured "
                        "inside a jit root constant-folds into the HLO "
                        "(multi-MB literals blow up the compile) — pass "
                        "it as an operand"))

    # ------------------------------------------------- manifest + coverage
    def _manifest_findings(self, ctx: FileContext, mi) -> List[Finding]:
        from tools.analysis.kernel_manifest import (check_manifest,
                                                    load_manifest)
        manifest = load_manifest(self.manifest_path)
        out: List[Finding] = []
        for fam, code, msg in check_manifest(manifest):
            out.append(Finding(ctx.relpath, 1, self.name, code, msg,
                               symbol="<manifest>", src=f"family {fam}"))
        prewarm = mi.constants.get("_PREWARM_SHAPES")
        for code, token, msg in coverage_problems(manifest, prewarm):
            out.append(Finding(ctx.relpath, 1, self.name, code, msg,
                               symbol="<manifest>", src=token))
        return out
