"""Operator tooling (ref: src/yb/tools — yb-admin, ysck, ldb; bin/yugabyted)."""
