"""error-propagation: except handlers on durability-critical paths must
route the error, re-raise, or carry an explicit containment marker.

The PR 1 containment contract: a background I/O failure on a flush,
compaction or WAL path must surface — to the DB background-error slot
(storage/db.py), the WAL seal (consensus/log.py `_fail`), a tablet
FAILED transition, or at minimum a raise that the maintenance machinery
sees. The swallowed-errors pass catches the blatant form (broad except,
body discards); this pass is the strict, whole-program form: ANY
`except` handler — broad or narrow — lexically inside a function
reachable from a flush/compaction/WAL seed must

  - re-raise (any `raise` in the handler), or
  - route the error (TRACE(...) / background_error / mark_failed /
    `_fail` / set_background_error — the swallowed-errors routing set),
    or
  - carry `# yblint: contained(<reason>)` on the except line, declaring
    the degradation deliberate and explaining why it is safe.

Seeds (whole-program call graph, so a helper three modules away is still
on the path):
  - every function whose name contains `flush` or `compact`;
  - every function whose name contains `nemesis`, `chaos` or `cancel`
    (PR 6: the chaos layer and the pipeline-cancellation paths — a
    swallowed error in fault injection makes chaos tests pass
    vacuously, and one in a cancellation path turns clean aborts into
    hangs or leaks);
  - every function whose name contains `scrub`, `integrity`, `shadow` or
    `corrupt` (PR 8: the data-integrity loop — a swallowed error in the
    scrubber or shadow verifier means corruption detected but never
    routed to repair, the exact dead end this code exists to close);
  - every function whose name contains `vouch` or `follower_read`
    (PR 11: the follower-read gate — a swallowed error here lets an
    unvetted replica serve reads), and every function of the client
    batcher (client/session.py: a swallowed send error in flush turns
    an unacked batch into a silently "acked" one);
  - every function of the WAL module (consensus/log.py), the nemesis
    rule engine (rpc/nemesis.py), the chaos controller
    (integration/chaos.py) and the integrity core
    (storage/integrity.py);
  - any function marked `# yblint: durability-path` on its def line.
Reachability includes weak callback edges (`Thread(target=f)`), so the
pipeline's ingest/decode worker closures are covered.

Findings are reported for files under storage/, consensus/, tablet/,
rpc/, integration/, ops/ and tserver/ — the layers whose silent
degradation loses data or silently un-injects faults (tserver/ joined
with the scrubber: its maintenance/digest paths route corruption).
`__del__` bodies are exempt (teardown is unroutable).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from tools.analysis.core import AnalysisPass, FileContext, Finding
from tools.analysis.project_index import ProjectIndex

PASS_NAME = "error-propagation"

DEFAULT_DIRS = ("yugabyte_tpu/storage", "yugabyte_tpu/consensus",
                "yugabyte_tpu/tablet", "yugabyte_tpu/rpc",
                "yugabyte_tpu/integration", "yugabyte_tpu/ops",
                "yugabyte_tpu/tserver", "yugabyte_tpu/client")
_SEED_NAME_RE = re.compile(
    r"flush|compact|nemesis|chaos|cancel|scrub|integrity|shadow|corrupt"
    r"|vouch|follower_read"
    # PR 12 overload protection: a swallowed error anywhere in the
    # shedding machinery silently converts "reject retryably" into
    # "drop on the floor" — the exact failure the soak's
    # zero-acked-loss invariant exists to catch. (\b guards keep
    # 'shed' from seeding every 'flushed'/'pushed'/'finished'.)
    r"|throttle|overload|admission|\bshed|_shed\b"
    # PR 13 query pushdown: a swallowed error in the fused-scan fallback
    # machinery would silently serve WRONG RESULTS instead of routing
    # the query back to the byte-identical host path
    r"|pushdown|scan_spec|scan_filtered|scan_aggregate"
    # PR 16 bucket health: a swallowed error in the routing state
    # machine silently freezes a bucket in the wrong state — a parked
    # bucket never re-promotes (perf rots) or a failing one never
    # demotes (faults keep burning retries)
    r"|health|probe|promote|demote",
    re.IGNORECASE)
_WAL_MODULE_SUFFIX = ".consensus.log"
_SEED_MODULE_SUFFIXES = (_WAL_MODULE_SUFFIX, ".rpc.nemesis",
                         ".integration.chaos", ".storage.integrity",
                         # PR 11: the client batcher — a swallowed send
                         # error in flush turns an unacked batch into a
                         # silently "acked" one
                         ".client.session",
                         # PR 12: the write-admission state machine —
                         # a contained signal-read error would silently
                         # disable a shedding arm under the exact load
                         # that needs it
                         ".tablet.admission",
                         # PR 13: the pushdown compile-subset classifier
                         # — a swallowed classification error turns
                         # "fall back host-side" into a wrong answer
                         ".docdb.scan_spec",
                         # PR 16: the bucket-health board — every device
                         # dispatch site routes through it, so a
                         # swallowed error here mis-routes ALL kernel
                         # families at once
                         ".storage.bucket_health",
                         # PR 17: the telemetry timebase — a silently
                         # dead scrape source leaves flat-lined series
                         # that read as "healthy and idle" during the
                         # exact incident the history exists to explain
                         ".utils.timeseries")
_MARKER_RE = re.compile(r"#\s*yblint:\s*contained\(")
_DEF_MARKER = "# yblint: durability-path"
_ROUTING_NAMES = ("TRACE", "trace")
_ROUTING_ATTRS = ("background_error", "set_background_error",
                  "mark_failed", "_fail")


def _routes_error(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name in _ROUTING_NAMES or any(a in name
                                             for a in _ROUTING_ATTRS):
                return True
    return False


def _seeds(index: ProjectIndex) -> Set[str]:
    out: Set[str] = set()
    for fi in index.functions.values():
        if _SEED_NAME_RE.search(fi.node.name):
            out.add(fi.key)
        elif fi.modname.endswith(_SEED_MODULE_SUFFIXES):
            out.add(fi.key)
        else:
            mi = index.modules.get(fi.modname)
            if mi is not None and _DEF_MARKER in \
                    mi.ctx.line_text(fi.node.lineno):
                out.add(fi.key)
    return out


class ErrorPropagationPass(AnalysisPass):
    name = PASS_NAME
    needs_index = True

    def __init__(self, dirs=DEFAULT_DIRS):
        self.dirs = tuple(d.rstrip("/") + "/" for d in dirs)

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.dirs)

    def run(self, ctx: FileContext, index: Optional[ProjectIndex] = None
            ) -> List[Finding]:
        if index is None:
            index = ProjectIndex([ctx])
        reachable: Set[str] = index.memo(
            "error_propagation.reachable",
            lambda: index.reachable(sorted(_seeds(index))))
        if not reachable:
            return []
        out: List[Finding] = []
        for node in ctx.nodes_of(ast.ExceptHandler):
            fn = ctx.enclosing_function(node)
            if fn is None or fn.name == "__del__":
                continue
            if not self._on_critical_path(ctx, index, fn, reachable):
                continue
            if _routes_error(node):
                continue
            if _MARKER_RE.search(ctx.line_text(node.lineno)):
                continue
            if "lint: swallow-ok" in ctx.line_text(node.lineno):
                continue  # legacy waiver (swallowed-errors era)
            out.append(ctx.finding(
                self.name, "unrouted-except", node,
                f"except on a durability path ({fn.name}) neither "
                "re-raises nor routes the error — raise, route to the "
                "background-error slot / TRACE, or mark the line "
                "`# yblint: contained(<why this is safe>)`"))
        return out

    def _on_critical_path(self, ctx: FileContext, index: ProjectIndex,
                          fn: ast.AST, reachable: Set[str]) -> bool:
        """The handler's function — or any enclosing function (a nested
        worker closure runs in its parent's dynamic extent) — is
        reachable from a seed."""
        cur: Optional[ast.AST] = fn
        while cur is not None:
            key = index.key_of(cur)
            if key is not None and key in reachable:
                return True
            cur = ctx.enclosing_function(cur)
        return False
