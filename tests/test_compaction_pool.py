"""Mesh-sharded compaction pool: multi-tablet differential suite.

N tablets compacted concurrently through the pool must be byte-identical
to sequential single-device runs; the scheduler must stay fair under a
saturating tablet; cancellation mid-job sweeps partial outputs with zero
leaked pins; a device fault in one wave quarantines the bucket and
completes every co-scheduled job natively instead of aborting them.
"""

import glob
import os
import threading
import time

import numpy as np
import pytest
import jax

from bench import synth_ycsb_runs, _attach_values, _split_runs
from yugabyte_tpu.ops import device_faults
from yugabyte_tpu.ops.merge_gc import GCParams
from yugabyte_tpu.parallel.mesh import make_mesh
from yugabyte_tpu.storage import offload_policy
from yugabyte_tpu.storage.compaction import run_compaction_job
from yugabyte_tpu.storage.device_cache import (DeviceSlabCache,
                                               NamespacedSlabCache)
from yugabyte_tpu.storage.sst import (Frontier, SSTReader, SSTWriter,
                                      data_file_name)
from yugabyte_tpu.tserver.compaction_pool import CompactionPool, PoolRequest
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.cancellation import (CancellationToken,
                                             OperationCancelled)

CUTOFF = 10_000_000 << 12


@pytest.fixture
def pool():
    p = CompactionPool(make_mesh(8))
    yield p
    p.shutdown()
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()


def _write_tablet_inputs(tmp_path, tag, n=12000, k=4, seed=0):
    slab, offsets = synth_ycsb_runs(n, k, n // 2, seed=seed)
    _attach_values(slab, 16)
    runs = _split_runs(slab, offsets)
    d = tmp_path / tag
    d.mkdir()
    paths = []
    for i, sub in enumerate(runs):
        p = str(d / f"{i:06d}.sst")
        SSTWriter(p).write(sub, Frontier())
        paths.append(p)
    return paths


def _out_bytes(result):
    blobs = []
    for _fid, p, _props in result.outputs:
        with open(p, "rb") as f:
            blobs.append(f.read())
        with open(data_file_name(p), "rb") as f:
            blobs.append(f.read())
    return blobs


def _merge_jobs(n_jobs, n=16000, seed0=0):
    jobs = []
    for j in range(n_jobs):
        slab, offsets = synth_ycsb_runs(n, 4, n // 2, seed=seed0 + j)
        jobs.append(_split_runs(slab, offsets))
    return jobs


def test_pool_differential_byte_identical(tmp_path, pool):
    """Concurrent pooled compactions == sequential single-device runs,
    byte for byte, with zero leaked pins and outputs resident-installed
    into each tablet's shard partition."""
    shared = DeviceSlabCache(jax.devices()[0], capacity_bytes=1 << 30)
    tablets = {f"t{t}": _write_tablet_inputs(tmp_path, f"in{t}", seed=t)
               for t in range(4)}
    handles = {}
    caches = {}
    for tid, paths in tablets.items():
        readers = [SSTReader(p) for p in paths]
        cache = pool.partition_for(shared, f"db-{tid}", tid)
        for fid, r in enumerate(readers):
            cache.stage(fid, r.read_all())
        caches[tid] = cache
        outd = tmp_path / f"pool_out_{tid}"
        outd.mkdir()
        ids = iter(range(100, 10_000))
        handles[tid] = (pool.submit(tid, PoolRequest(
            inputs=readers, out_dir=str(outd),
            new_file_id=lambda it=ids: next(it),
            history_cutoff_ht=CUTOFF, is_major=True,
            input_ids=list(range(len(readers))),
            device_cache=cache)), readers)
    results = {}
    for tid, (h, readers) in handles.items():
        results[tid] = h.result(timeout=300)
        for r in readers:
            r.close()
    assert shared.pinned_count() == 0, "leaked pins after pooled jobs"
    snap = pool.snapshot()
    assert snap["waves"] >= 1
    assert snap["wave_jobs"] >= 4
    # outputs installed into the per-shard partitions (resident chain
    # survives sharding) — at least the single-file outputs
    cache_snap = shared.snapshot()
    assert "shards" in cache_snap and cache_snap["entries"] > 0
    for tid, paths in tablets.items():
        readers = [SSTReader(p) for p in paths]
        outd = tmp_path / f"seq_out_{tid}"
        outd.mkdir()
        ids = iter(range(100, 10_000))
        res = run_compaction_job(readers, str(outd),
                                 lambda it=ids: next(it), CUTOFF, True,
                                 device=jax.devices()[0])
        for r in readers:
            r.close()
        assert res.rows_out == results[tid].rows_out, tid
        assert _out_bytes(res) == _out_bytes(results[tid]), \
            f"{tid}: pooled outputs differ from the sequential run"


def test_pool_fairness_under_saturation(pool):
    """A tablet saturating the queue must not starve a light tablet: the
    light tablet's jobs complete long before the heavy backlog drains."""
    heavy_jobs = _merge_jobs(24, n=8000)
    light_jobs = _merge_jobs(2, n=8000, seed0=100)
    heavy = [pool.submit("heavy", PoolRequest(
        inputs=[], out_dir="", new_file_id=None,
        history_cutoff_ht=CUTOFF, is_major=True, slabs=runs))
        for runs in heavy_jobs]
    light = [pool.submit("light", PoolRequest(
        inputs=[], out_dir="", new_file_id=None,
        history_cutoff_ht=CUTOFF, is_major=True, slabs=runs))
        for runs in light_jobs]
    for h in light:
        h.result(timeout=300)
    for h in heavy:
        h.result(timeout=600)
    light_last = max(h.finished_at for h in light)
    after_light = sum(1 for h in heavy if h.finished_at > light_last)
    # without fairness the light tablet (submitted last) would wait for
    # the entire heavy backlog; with deficit scheduling a healthy slice
    # of the heavy queue must still be pending when light completes
    assert after_light >= 8, after_light


def test_pool_merge_decisions_match_single_device(pool):
    """Merge-only pool jobs return the exact decisions of a sequential
    single-device launch over the same runs."""
    from yugabyte_tpu.ops import run_merge
    jobs = _merge_jobs(6, n=10000)
    handles = [pool.submit(f"t{i}", PoolRequest(
        inputs=[], out_dir="", new_file_id=None,
        history_cutoff_ht=CUTOFF, is_major=True, slabs=runs))
        for i, runs in enumerate(jobs)]
    for h, runs in zip(handles, jobs):
        surv, mk_surv = h.result(timeout=300)
        perm, keep, mk = run_merge.merge_and_gc_runs(
            runs, GCParams(CUTOFF, True))
        assert np.array_equal(surv, perm[keep])
        assert np.array_equal(mk_surv, mk[keep])


def test_pool_cancellation_sweeps_partial_outputs(tmp_path, pool):
    """Cancel mid-job: partial outputs are swept, the handle raises
    OperationCancelled, no pins leak, co-scheduled jobs are unaffected."""
    paths = _write_tablet_inputs(tmp_path, "in_cancel", n=50000, seed=7)
    other_paths = _write_tablet_inputs(tmp_path, "in_other", n=12000,
                                       seed=8)
    readers = [SSTReader(p) for p in paths]
    other_readers = [SSTReader(p) for p in other_paths]
    outd = tmp_path / "out_cancel"
    outd.mkdir()
    outd2 = tmp_path / "out_other"
    outd2.mkdir()
    old_rows = flags.get_flag("compaction_max_output_entries_per_sst")
    old_rate = flags.get_flag("compaction_rate_bytes_per_sec")
    flags.set_flag("compaction_max_output_entries_per_sst", 4000)
    # pace file writes so the watcher below reliably lands its cancel
    # between two output spans
    flags.set_flag("compaction_rate_bytes_per_sec", 200_000)
    token = CancellationToken("test job")
    try:
        ids = iter(range(100, 10_000))
        h = pool.submit("victim", PoolRequest(
            inputs=readers, out_dir=str(outd),
            new_file_id=lambda: next(ids),
            history_cutoff_ht=CUTOFF, is_major=True), cancel=token)
        ids2 = iter(range(100, 10_000))
        h2 = pool.submit("bystander", PoolRequest(
            inputs=other_readers, out_dir=str(outd2),
            new_file_id=lambda: next(ids2),
            history_cutoff_ht=CUTOFF, is_major=True))

        def _watch():
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if glob.glob(str(outd / "*.sst")):
                    token.cancel("test cancel mid-write")
                    return
                time.sleep(0.001)
            token.cancel("test cancel (no file seen)")

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        with pytest.raises(OperationCancelled):
            h.result(timeout=300)
        t.join(timeout=60)
        res2 = h2.result(timeout=300)   # bystander completes normally
        assert res2.rows_out > 0
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old_rows)
        flags.set_flag("compaction_rate_bytes_per_sec", old_rate)
        for r in readers + other_readers:
            r.close()
    # the unwind swept every partial output (base + data files)
    assert glob.glob(str(outd / "*.sst*")) == []
    assert pool.snapshot()["cancelled"] >= 1


def test_pool_wave_fault_quarantines_without_collateral(tmp_path, pool):
    """A device fault during a pooled wave quarantines the shape bucket
    and completes EVERY wave job natively, byte-identically — one bad
    shard never aborts co-scheduled tablets' jobs."""
    offload_policy.bucket_quarantine().clear()
    tablets = {f"f{t}": _write_tablet_inputs(tmp_path, f"inf{t}", seed=20 + t)
               for t in range(2)}
    device_faults.arm("runtime", site="dispatch", count=1)
    handles = {}
    try:
        for tid, paths in tablets.items():
            readers = [SSTReader(p) for p in paths]
            outd = tmp_path / f"pool_out_{tid}"
            outd.mkdir()
            ids = iter(range(100, 10_000))
            handles[tid] = (pool.submit(tid, PoolRequest(
                inputs=readers, out_dir=str(outd),
                new_file_id=lambda it=ids: next(it),
                history_cutoff_ht=CUTOFF, is_major=True)), readers)
        results = {}
        for tid, (h, readers) in handles.items():
            results[tid] = h.result(timeout=300)   # NOT aborted
            for r in readers:
                r.close()
    finally:
        device_faults.disarm_all()
    snap = pool.snapshot()
    assert snap["wave_faults"] >= 1
    assert snap["native_completions"] >= 2
    assert offload_policy.bucket_quarantine().snapshot(), \
        "wave fault must quarantine the shape bucket"
    offload_policy.bucket_quarantine().clear()
    # byte-identical to the sequential native path over the same inputs
    for tid, paths in tablets.items():
        readers = [SSTReader(p) for p in paths]
        outd = tmp_path / f"seq_out_{tid}"
        outd.mkdir()
        ids = iter(range(100, 10_000))
        res = run_compaction_job(readers, str(outd),
                                 lambda it=ids: next(it), CUTOFF, True,
                                 device="native")
        for r in readers:
            r.close()
        assert _out_bytes(res) == _out_bytes(results[tid]), tid


def test_pool_bucket_demotion_routes_native(pool):
    """RESYSTANCE-style measured routing through the health board: once
    the measured device rate of a bucket falls under its native rate,
    later jobs of that bucket run natively (and the snapshot says so)."""
    from yugabyte_tpu.storage.bucket_health import health_board
    board = health_board()
    board.reset()
    jobs = _merge_jobs(2, n=8000)
    h = pool.submit("warm", PoolRequest(
        inputs=[], out_dir="", new_file_id=None,
        history_cutoff_ht=CUTOFF, is_major=True, slabs=jobs[0]))
    h.result(timeout=300)
    snap_keys = [tuple(rec["bucket"])
                 for rec in board.snapshot()["keys"]
                 if rec["family"] == "run_merge_fused"
                 and rec["device_obs"] > 0]
    assert snap_keys, "wave must record a device rate on the board"
    bucket = snap_keys[0]
    # force the demotion crossover with board observations: native
    # measured far faster, then enough slow device results to clear the
    # warmup guard (one cold-compile sample must not demote alone)
    board.record_native("run_merge_fused", bucket, 10**9, 1.0)
    for _ in range(int(flags.get_flag("bucket_health_warmup_obs"))):
        board.record_device("run_merge_fused", bucket, 1, 1.0)
    assert board.state("run_merge_fused", bucket) == "degraded"
    before = pool.snapshot()["native_completions"]
    h2 = pool.submit("warm", PoolRequest(
        inputs=[], out_dir="", new_file_id=None,
        history_cutoff_ht=CUTOFF, is_major=True, slabs=jobs[1]))
    surv, mk_surv = h2.result(timeout=300)
    assert pool.snapshot()["native_completions"] == before + 1
    assert pool.snapshot()["bucket_rates"][
        f"k{bucket[0]}_m{bucket[1]}"]["demoted"]
    # native completion computes identical decisions
    from yugabyte_tpu.ops import run_merge
    perm, keep, mk = run_merge.merge_and_gc_runs(
        jobs[1], GCParams(CUTOFF, True))
    assert np.array_equal(surv, perm[keep])
    assert np.array_equal(mk_surv, mk[keep])
    board.reset()
