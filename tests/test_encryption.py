"""Encryption at rest: Env layer, envelope keys, online enablement
(round-2 Missing #8; ref src/yb/encryption/encrypted_file.cc,
ent/src/yb/master/universe_key_registry_service.cc)."""

import os
import secrets

import pytest

# the AES-CTR cipher lives in the optional `cryptography` package; the
# whole encryption feature is gated on it
pytest.importorskip("cryptography")

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.utils import env as env_mod


@pytest.fixture()
def encrypted_env():
    keys = env_mod.UniverseKeys()
    keys.add("uk-test", secrets.token_bytes(32))
    env_mod.enable_encryption(keys)
    yield env_mod.get_env()
    env_mod.disable_encryption()


def test_env_roundtrip_and_random_access(tmp_path, encrypted_env):
    env = encrypted_env
    data = bytes(range(256)) * 100
    p = str(tmp_path / "f")
    env.write_file(p, data)
    raw = open(p, "rb").read()
    assert raw[:8] == b"YBENCv1\x00"
    assert data[:64] not in raw          # ciphertext, not plaintext
    assert env.read_file(p) == data
    r = env.open_random(p)
    for off, size in ((0, 10), (17, 33), (4000, 256), (25599, 1)):
        assert r.pread(size, off) == data[off: off + size]
    assert r.size() == len(data)
    r.close()


def test_env_append_reopen_continues_stream(tmp_path, encrypted_env):
    env = encrypted_env
    p = str(tmp_path / "wal")
    a = env.open_append(p)
    a.append(b"hello ")
    a.flush()
    a.close()
    a = env.open_append(p)          # reopen mid-stream
    assert a.offset == 6
    a.append(b"world")
    a.flush()
    a.close()
    assert env.read_file(p) == b"hello world"


def test_env_legacy_plaintext_fallback(tmp_path, encrypted_env):
    env = encrypted_env
    p = str(tmp_path / "legacy")
    with open(p, "wb") as f:
        f.write(b"plain old bytes")
    assert env.read_file(p) == b"plain old bytes"
    r = env.open_random(p)
    assert r.pread(5, 6) == b"old b"
    r.close()


def test_env_torn_header_fails_closed(tmp_path, encrypted_env):
    """A file truncated mid-header (crash during create) must fail loudly
    on every access path — never key the cipher with garbage bytes."""
    env = encrypted_env
    p = str(tmp_path / "full")
    env.write_file(p, b"x" * 500)
    raw = open(p, "rb").read()
    # header = magic(8) + kid_len(2) + kid + nonce(16) + wrapped(32)
    hlen = 8 + 2 + len("uk-test") + 16 + 32
    for cut in (9, 10, 15, hlen - 1):
        torn = str(tmp_path / f"torn{cut}")
        with open(torn, "wb") as f:
            f.write(raw[:cut])
        with pytest.raises(ValueError):
            env.read_file(torn)
        with pytest.raises(ValueError):
            env.open_random(torn)
        with pytest.raises(ValueError):
            env.open_append(torn)  # reopen-for-append parses the header too


def test_env_truncated_header_leaves_no_fd_leak(tmp_path, encrypted_env):
    import resource
    env = encrypted_env
    p = str(tmp_path / "f")
    env.write_file(p, b"data")
    torn = str(tmp_path / "torn")
    with open(torn, "wb") as f:
        f.write(open(p, "rb").read()[:20])
    soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    for _ in range(min(soft + 10, 2000)):
        with pytest.raises(ValueError):
            env.open_random(torn)  # leaked fds would exhaust the limit


def test_env_unknown_key_fails_closed(tmp_path):
    keys = env_mod.UniverseKeys()
    keys.add("uk-a", secrets.token_bytes(32))
    env_mod.enable_encryption(keys)
    try:
        p = str(tmp_path / "f")
        env_mod.get_env().write_file(p, b"secret")
        other = env_mod.UniverseKeys()
        other.add("uk-b", secrets.token_bytes(32))
        env_mod.enable_encryption(other)
        with pytest.raises(KeyError):
            env_mod.get_env().read_file(p)
    finally:
        env_mod.disable_encryption()


def test_encrypted_db_write_flush_compact_read(tmp_path, encrypted_env):
    db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
    marker = b"SUPERSECRETVALUE"
    for i in range(40):
        key = SubDocKey(DocKey(range_components=(f"r{i:03d}",)),
                        (("col", 0),)).encode(include_ht=False)
        db.write_batch([(key, DocHybridTime(HybridTime((i + 1) << 12), 0),
                         Value(primitive=marker.decode()).encode())])
        if i % 13 == 12:
            db.flush()
    db.flush()
    db.compact_all()
    # every SST byte on disk is ciphertext
    for name in os.listdir(str(tmp_path / "db")):
        if ".sst" in name:
            raw = open(str(tmp_path / "db" / name), "rb").read()
            assert raw[:8] == b"YBENCv1\x00", name
            assert marker not in raw, name
    # reads (incl. after reopen) decrypt transparently
    got = db.get(SubDocKey(DocKey(range_components=("r005",)),
                           (("col", 0),)).encode(include_ht=False))
    assert got is not None and marker.decode() in repr(got)
    db.close()
    db2 = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
    got = db2.get(SubDocKey(DocKey(range_components=("r017",)),
                            (("col", 0),)).encode(include_ht=False))
    assert got is not None
    db2.close()


def test_cluster_online_encryption_enablement(tmp_path):
    """rotate_universe_key on the master: keys flow to tservers via
    heartbeats and NEW storage files (WAL + SSTs) encrypt, while the
    pre-enablement plaintext files stay readable — online enablement."""
    import time

    from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
    from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
    from yugabyte_tpu.integration.mini_cluster import (
        MiniCluster, MiniClusterOptions)
    from yugabyte_tpu.utils import flags

    flags.set_flag("replication_factor", 3)
    mc = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path / "enc"))).start()
    try:
        client = mc.new_client()
        client.create_namespace("e")
        schema = Schema([ColumnSchema("k", DataType.STRING),
                         ColumnSchema("v", DataType.STRING)], 1, 0)
        t = client.create_table("e", "t", schema, num_tablets=1)
        mc.wait_for_table_leaders("e", "t")  # don't race the election
        client.write(t, [QLWriteOp(WriteOpKind.INSERT,
                                   DocKey(hash_components=("before",)),
                                   {"v": "plaintext-era"})])
        client._master_call("rotate_universe_key")
        time.sleep(0.6)  # keys ride the next heartbeats
        # a tablet created AFTER enablement writes encrypted WAL segments
        # (already-open plaintext segments keep appending until they roll)
        t2 = client.create_table("e", "t2", schema, num_tablets=1)
        mc.wait_for_table_leaders("e", "t2")  # don't race the election
        marker = "POSTENCRYPTIONSECRET"
        for i in range(30):
            client.write(t2, [QLWriteOp(
                WriteOpKind.INSERT, DocKey(hash_components=(f"k{i}",)),
                {"v": marker})])
        deadline = time.monotonic() + 20
        found_encrypted_wal = False
        while time.monotonic() < deadline and not found_encrypted_wal:
            for dirpath, _d, files in os.walk(str(tmp_path / "enc")):
                for f in files:
                    if f.startswith("wal-"):
                        raw = open(os.path.join(dirpath, f), "rb").read()
                        if raw[:8] == b"YBENCv1\x00" and len(raw) > 60:
                            found_encrypted_wal = True
                            assert marker.encode() not in raw
            time.sleep(0.2)
        assert found_encrypted_wal, "no encrypted WAL segment appeared"
        # both eras readable
        row = client.read_row(t, DocKey(hash_components=("before",)))
        assert row.to_dict(schema)["v"] == "plaintext-era"
        row = client.read_row(t2, DocKey(hash_components=("k7",)))
        assert row.to_dict(schema)["v"] == marker
        client.close()
    finally:
        mc.shutdown()
        env_mod.disable_encryption()
